"""ServeController: reconciles deployment state to replica actors.

Reference: python/ray/serve/_private/controller.py (ServeController) +
deployment_state.py (target vs running replica reconciliation) +
autoscaling_policy.py (ongoing-requests-per-replica policy).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.replica import ServeReplica


class _DeploymentState:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.replicas: List = []
        self.version = 0
        self.target = spec["num_replicas"]
        self.last_scale_time = 0.0
        self.scale_signal_since: Optional[float] = None
        self.scale_signal_dir = 0
        self.next_replica_id = 0
        self.replica_ids: List[int] = []  # parallel to self.replicas
        # replica_id -> (ongoing, timestamp), pushed by replicas
        self.stats: Dict[int, tuple] = {}
        # replica_id -> spawn time: a replica gets a startup grace window
        # before the liveness sweep may declare it dead on silence
        self.spawned_at: Dict[int, float] = {}


@ray_tpu.remote(num_cpus=0)
class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        # app -> deployment name -> state
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._ingress: Dict[str, str] = {}
        self._stop = False
        self._loop = threading.Thread(target=self._control_loop, daemon=True)
        self._loop.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(self, app_name: str, specs: List[Dict[str, Any]],
                           ingress: Optional[str] = None):
        with self._lock:
            if ingress is not None:
                self._ingress[app_name] = ingress
            app = self._apps.setdefault(app_name, {})
            new_names = {spec["name"] for spec in specs}
            # deployments dropped from the app spec are torn down (reference:
            # deployment_state reconciles the FULL target set)
            for name in list(app):
                if name not in new_names:
                    for r in list(app[name].replicas):
                        self._kill_replica(app[name], r)
                    del app[name]
            for spec in specs:
                name = spec["name"]
                old = app.get(name)
                if old is not None:
                    # in-place update: new code/config, replace replicas
                    for r in list(old.replicas):
                        self._kill_replica(old, r)
                    old.spec = spec
                    old.replicas = []
                    old.replica_ids = []
                    old.target = spec["num_replicas"]
                    old.version += 1
                else:
                    app[name] = _DeploymentState(spec)
            self._reconcile_locked()
        return True

    def delete_application(self, app_name: str):
        with self._lock:
            app = self._apps.pop(app_name, {})
            for st in app.values():
                for r in list(st.replicas):
                    self._kill_replica(st, r)
        return True

    def shutdown(self):
        with self._lock:
            self._stop = True
            for app in self._apps.values():
                for st in app.values():
                    for r in list(st.replicas):
                        self._kill_replica(st, r)
            self._apps.clear()
        return True

    # -------------------------------------------------------------- queries
    def get_replicas(self, app_name: str, deployment_name: str):
        with self._lock:
            st = self._state(app_name, deployment_name)
            return {
                "replicas": list(st.replicas),
                "version": st.version,
                "fast_path": bool(st.spec.get("fast_path")),
            }

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            return self._ingress.get(app_name)

    def get_replica_version(self, app_name: str, deployment_name: str) -> int:
        with self._lock:
            st = self._apps.get(app_name, {}).get(deployment_name)
            return st.version if st else -1

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                app: {
                    name: {
                        "num_replicas": len(st.replicas),
                        "target": st.target,
                        "version": st.version,
                    }
                    for name, st in deps.items()
                }
                for app, deps in self._apps.items()
            }

    def _state(self, app_name, deployment_name) -> _DeploymentState:
        st = self._apps.get(app_name, {}).get(deployment_name)
        if st is None:
            raise KeyError(f"unknown deployment {app_name}/{deployment_name}")
        return st

    # ----------------------------------------------------------- reconcile
    def _kill(self, replica):
        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _kill_replica(self, st: "_DeploymentState", replica):
        """Kill + retire: drop the rid from live set and stats so a leaked
        metrics thread (daemon threads can't be interrupted in local mode)
        can never re-register a dead replica into autoscaling."""
        try:
            idx = st.replicas.index(replica)
        except ValueError:
            idx = -1
        if idx >= 0 and idx < len(st.replica_ids):
            rid = st.replica_ids[idx]
            st.stats.pop(rid, None)
            st.spawned_at.pop(rid, None)
        try:
            # best-effort, fire-and-forget thread stop on a replica that is
            # about to be killed — there is no result worth fetching
            replica.stop_metrics.remote()  # ray-lint: disable=dropped-object-ref
        except Exception:
            pass
        self._kill(replica)

    def _reconcile_locked(self):
        for app_name, deps in self._apps.items():
            for name, st in deps.items():
                delta = st.target - len(st.replicas)
                if delta > 0:
                    spec = st.spec
                    opts = dict(spec["ray_actor_options"])
                    opts.setdefault("num_cpus", 0.1)
                    opts["max_concurrency"] = max(
                        int(spec["max_ongoing_requests"]), 2
                    )
                    for _ in range(delta):
                        rid = st.next_replica_id
                        st.next_replica_id += 1
                        st.replicas.append(
                            ServeReplica.options(**opts).remote(
                                spec["func_or_class"],
                                spec["init_args"],
                                spec["init_kwargs"],
                                spec.get("user_config"),
                                identity=(app_name, name, rid),
                                max_ongoing_requests=int(
                                    spec["max_ongoing_requests"]
                                ),
                            )
                        )
                        st.replica_ids.append(rid)
                        st.spawned_at[rid] = time.time()
                    st.version += 1
                elif delta < 0:
                    for r in list(st.replicas[st.target:]):
                        self._kill_replica(st, r)
                    st.replicas = st.replicas[: st.target]
                    st.replica_ids = st.replica_ids[: st.target]
                    st.version += 1

    # --------------------------------------------------------- autoscaling
    # a replica whose stats push has been silent this long (and that is
    # past its startup grace) gets a health probe; probe failure = dead.
    # Generous on purpose: GIL contention on a loaded 2-CPU host delays
    # pushes, and a false kill churns the very replicas serving traffic.
    REPLICA_SILENT_S = 5.0

    def _control_loop(self):
        while not self._stop:
            time.sleep(0.25)
            try:
                self._autoscale_tick()
            except Exception:
                pass
            try:
                self._liveness_tick()
            except Exception:
                pass

    def _liveness_tick(self):
        """Detect crashed replicas and respawn them (reference:
        deployment_state's replica health reconciliation). A replica
        killed by a node/worker death stops pushing stats; after the
        silence window it gets one direct health probe, and a failed
        probe retires it so _reconcile_locked brings the deployment back
        to target — the reconciliation the serve_storm chaos runs lean on
        (the task-layer handle AND the fast-path router both just need
        fresh membership; re-routing is theirs)."""
        now = time.time()
        suspects = []  # (st, replica, rid)
        with self._lock:
            for deps in self._apps.values():
                for st in deps.values():
                    for idx, rid in enumerate(st.replica_ids):
                        if now - st.spawned_at.get(rid, now) < \
                                self.REPLICA_SILENT_S:
                            continue
                        rec = st.stats.get(rid)
                        if rec is not None and \
                                now - rec[1] < self.REPLICA_SILENT_S:
                            continue
                        suspects.append((st, st.replicas[idx], rid))
        dead = []
        for st, replica, rid in suspects[:4]:  # bound probe work per tick
            try:
                ray_tpu.get(replica.health_check.remote(), timeout=2.0)
                with self._lock:
                    # answered: treat the probe as a fresh stats sample so
                    # a quiet-but-alive replica isn't re-probed every tick
                    st.stats.setdefault(rid, (0, time.time()))
                    st.stats[rid] = (st.stats[rid][0], time.time())
            except Exception:  # noqa: BLE001 - dead/unreachable
                dead.append((st, replica, rid))
        if not dead:
            return
        with self._lock:
            for st, replica, rid in dead:
                try:
                    idx = st.replicas.index(replica)
                except ValueError:
                    continue  # already retired by a racing path
                st.replicas.pop(idx)
                rid = st.replica_ids.pop(idx)
                st.stats.pop(rid, None)
                st.spawned_at.pop(rid, None)
                self._kill_replica(st, replica)
                st.version += 1
            self._reconcile_locked()

    def record_stats(self, identity, ongoing: int):
        app_name, dep_name, rid = identity
        with self._lock:
            st = self._apps.get(app_name, {}).get(dep_name)
            if st is not None and rid in st.replica_ids:
                st.stats[rid] = (ongoing, time.time())
        return True

    def _autoscale_tick(self):
        with self._lock:
            states = [
                st
                for deps in self._apps.values()
                for st in deps.values()
                if st.spec.get("autoscaling_config") is not None
            ]
        for st in states:
            cfg = st.spec["autoscaling_config"]
            now = time.time()
            with self._lock:
                if not st.replicas:
                    continue
                # drop records from replicas that stopped reporting (killed)
                st.stats = {
                    rid: rec for rid, rec in st.stats.items()
                    if now - rec[1] < 10.0
                }
                fresh = [
                    ongoing for ongoing, ts in st.stats.values()
                    if now - ts < 2.0
                ]
            if not fresh:
                continue
            avg_ongoing = sum(fresh) / len(fresh)
            if avg_ongoing > cfg.target_ongoing_requests and st.target < cfg.max_replicas:
                direction, delay = 1, cfg.upscale_delay_s
            elif (
                avg_ongoing < cfg.target_ongoing_requests * 0.5
                and st.target > cfg.min_replicas
            ):
                direction, delay = -1, cfg.downscale_delay_s
            else:
                direction, delay = 0, 0.0
            with self._lock:
                if direction == 0 or direction != st.scale_signal_dir:
                    st.scale_signal_dir = direction
                    st.scale_signal_since = now if direction else None
                    continue
                if now - (st.scale_signal_since or now) >= delay:
                    st.target = min(
                        max(st.target + direction, cfg.min_replicas),
                        cfg.max_replicas,
                    )
                    st.scale_signal_since = now
                    self._reconcile_locked()
