"""Driver-side checkpoint bookkeeping: persist, rank, prune.

Reference: python/ray/train/_internal/checkpoint_manager.py
(_CheckpointManager — keeps num_to_keep checkpoints ordered by
checkpoint_score_attribute).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint, persist_checkpoint
from ray_tpu.air.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, run_dir: str, config: CheckpointConfig):
        self.run_dir = run_dir
        self.config = config
        self._kept: List[Tuple[float, str, Dict[str, Any]]] = []
        self._counter = 0
        self.latest: Optional[Checkpoint] = None

    def register(self, worker_ckpt_path: str, metrics: Dict[str, Any]) -> Checkpoint:
        dest = os.path.join(
            self.run_dir, f"checkpoint_{self._counter:06d}"
        )
        self._counter += 1
        ckpt = persist_checkpoint(Checkpoint.from_directory(worker_ckpt_path), dest)
        self.latest = ckpt
        attr = self.config.checkpoint_score_attribute
        if attr is not None:
            if attr not in metrics:
                # reference parity: a configured score attribute missing from
                # the report is an error, not a silent recency fallback
                raise ValueError(
                    f"checkpoint_score_attribute {attr!r} not in reported "
                    f"metrics {sorted(metrics)}"
                )
            score = float(metrics[attr])
            if self.config.checkpoint_score_order == "min":
                score = -score
        else:
            score = float(self._counter)  # recency order
        self._kept.append((score, dest, dict(metrics)))
        self._prune()
        return ckpt

    def _prune(self):
        k = self.config.num_to_keep
        if k is None or len(self._kept) <= k:
            return
        self._kept.sort(key=lambda t: t[0], reverse=True)
        for score, path, _ in self._kept[k:]:
            if self.latest is not None and path == self.latest.path:
                continue
            shutil.rmtree(path, ignore_errors=True)
        self._kept = [
            e for e in self._kept[:k]
        ] + [e for e in self._kept[k:] if self.latest and e[1] == self.latest.path]

    def best(self) -> Optional[Checkpoint]:
        if not self._kept:
            return self.latest
        best = max(self._kept, key=lambda t: t[0])
        return Checkpoint.from_directory(best[1]) if os.path.isdir(best[1]) else self.latest
