"""Worker-side training session: context, report(), get_checkpoint().

Reference: python/ray/train/_internal/session.py (_TrainSession, report,
get_context) — workers call ``train.report(metrics, checkpoint=...)`` which
synchronizes all ranks (a barrier) and ships rank-0's checkpoint to run
storage via the coordinator.

Implementation: each worker pushes to a ``_ReportBus`` actor whose ``push``
is a world-size barrier; the trainer drains completed rounds. Works in both
local (thread-actor) and cluster (process-worker) modes because the bus is an
ordinary actor reached through its handle.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint

_session = threading.local()


@dataclass
class TrainContext:
    """What a worker can ask about itself (reference:
    train/context.py TrainContext)."""

    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = "default"
    trial_name: str = "trial"
    trial_dir: str = ""
    trial_config: Dict[str, Any] = field(default_factory=dict)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _WorkerSession:
    def __init__(self, ctx: TrainContext, bus_handle, start_checkpoint_path):
        self.ctx = ctx
        self.bus = bus_handle
        self.iteration = 0
        self.start_checkpoint_path = start_checkpoint_path


def _install_session(ctx, bus_handle, start_checkpoint_path):
    _session.value = _WorkerSession(ctx, bus_handle, start_checkpoint_path)


def _clear_session():
    _session.value = None


def _get_session() -> Optional[_WorkerSession]:
    return getattr(_session, "value", None)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        # Driver-side / outside a worker: a degenerate 1-worker context,
        # matching the reference's behavior of tolerating non-session use.
        return TrainContext()
    return s.ctx


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint this run was (re)started from, if any (reference:
    train.get_checkpoint — the resume path after failure restart)."""
    s = _get_session()
    if s is None or not s.start_checkpoint_path:
        return None
    return Checkpoint.from_directory(s.start_checkpoint_path)


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (and optionally a checkpoint) for this iteration.
    Synchronizes all workers like the reference's train.report barrier."""
    s = _get_session()
    if s is None:
        return  # tolerated outside a session (reference parity)
    payload = {
        "rank": s.ctx.world_rank,
        "iteration": s.iteration,
        "metrics": dict(metrics),
        "checkpoint_path": checkpoint.path if checkpoint is not None else None,
        "checkpoint_ref": None,
        "time": time.time(),
    }
    if checkpoint is not None:
        # Ship contents through the object store so the driver can
        # materialize them even when the worker's filesystem isn't shared
        # (multi-node cluster mode); the driver prefers the local-path fast
        # path when it sees the same filesystem.
        import io
        import tarfile

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(checkpoint.path, arcname=".")
        payload["checkpoint_ref"] = ray_tpu.put(buf.getvalue())
    s.iteration += 1
    # Barrier: push returns once every rank has pushed this iteration.
    ray_tpu.get(s.bus.push.remote(payload))


@ray_tpu.remote(num_cpus=0)
class _ReportBus:
    """Coordinator actor: per-iteration barrier + report mailbox.

    max_concurrency must cover all workers blocking in push simultaneously
    plus the trainer's drain polls.
    """

    def __init__(self, world_size: int, barrier_timeout_s: float = 600.0):
        self._world = world_size
        self._timeout = barrier_timeout_s
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Dict[int, List[dict]] = {}
        self._complete: List[List[dict]] = []
        self._aborted = False

    def push(self, payload: dict) -> bool:
        it = payload["iteration"]
        with self._cv:
            self._pending.setdefault(it, []).append(payload)
            if len(self._pending[it]) == self._world:
                round_ = sorted(self._pending.pop(it), key=lambda p: p["rank"])
                self._complete.append(round_)
                self._cv.notify_all()
                return True
            deadline = time.time() + self._timeout
            while not self._aborted and it in self._pending:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"train.report barrier timed out at iteration {it}: "
                        f"{len(self._pending.get(it, []))}/{self._world} ranks"
                    )
                self._cv.wait(timeout=min(remaining, 1.0))
            if self._aborted:
                raise RuntimeError("training aborted")
        return True

    def drain(self) -> List[List[dict]]:
        with self._lock:
            out = self._complete
            self._complete = []
            return out

    def abort(self):
        with self._cv:
            self._aborted = True
            self._cv.notify_all()


def make_report_bus(world_size: int, barrier_timeout_s: float = 600.0):
    return _ReportBus.options(
        max_concurrency=world_size + 2, num_cpus=0
    ).remote(world_size, barrier_timeout_s)
