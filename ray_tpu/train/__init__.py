"""ray_tpu.train — distributed training orchestration.

Reference: python/ray/train/ (TorchTrainer, DataParallelTrainer,
train.report/get_context/get_checkpoint, Checkpoint, ScalingConfig/RunConfig).
The flagship here is JaxTrainer: worker-group actors each running one jitted
SPMD program over a mesh (SURVEY §3.5 — the framework orchestrates, the step
function owns the device).
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train.backend_executor import Backend, BackendExecutor
from ray_tpu.train.jax_trainer import JaxBackend, JaxTrainer
from ray_tpu.train.torch_trainer import (
    TorchBackend,
    TorchTrainer,
    prepare_data_loader,
    prepare_model,
)
from ray_tpu.train.jax_utils import (
    load_pytree,
    prepare_data_shard,
    prepare_mesh,
    save_pytree,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    report,
)
from ray_tpu.train.trainer import DataParallelTrainer, TrainingFailedError
from ray_tpu.train.worker_group import WorkerGroup

__all__ = [
    "Backend",
    "BackendExecutor",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxBackend",
    "JaxTrainer",
    "TorchBackend",
    "TorchTrainer",
    "prepare_data_loader",
    "prepare_model",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainingFailedError",
    "WorkerGroup",
    "get_checkpoint",
    "get_context",
    "load_pytree",
    "prepare_data_shard",
    "prepare_mesh",
    "report",
    "save_pytree",
]
