"""DataParallelTrainer: the Train entry point.

Reference: python/ray/train/data_parallel_trainer.py (DataParallelTrainer)
+ python/ray/train/base_trainer.py (BaseTrainer.fit). The reference routes
fit() through a 1-trial Tune run; here fit() drives the BackendExecutor
directly and ray_tpu.tune reuses this trainer as a trainable — same layering,
inverted dependency (Tune on Train instead of Train on Tune), which is the
cleaner factoring for a fresh build.

Failure handling (reference: FailureConfig.max_failures + Tune trial
restore): on worker-group failure the group is torn down and restarted from
the latest persisted checkpoint, surfaced to workers via
train.get_checkpoint().
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend_executor import Backend, BackendExecutor
from ray_tpu.train.checkpoint_manager import CheckpointManager


class TrainingFailedError(RuntimeError):
    pass


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[Backend] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    # ------------------------------------------------------------------- fit
    def fit(self) -> Result:
        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        run_dir = os.path.join(self.run_config.resolved_storage_path(), name)
        os.makedirs(run_dir, exist_ok=True)
        ckpt_mgr = CheckpointManager(run_dir, self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures
        attempts_left = float("inf") if max_failures < 0 else max_failures + 1

        metrics_history: list = []
        last_metrics: Dict[str, Any] = {}
        error: Optional[Exception] = None
        start_ckpt = self.resume_from_checkpoint

        while attempts_left > 0:
            attempts_left -= 1
            executor = BackendExecutor(
                self.scaling_config,
                backend=self.backend,
                experiment_name=name,
                trial_name=name,
                trial_dir=run_dir,
            )
            try:
                executor.start(
                    start_checkpoint=ckpt_mgr.latest or start_ckpt,
                    trial_config=self.train_loop_config,
                )
                futures = executor.run_training(
                    self.train_loop, self.train_loop_config
                )
                pending = list(futures)
                while pending:
                    done, pending = ray_tpu.wait(
                        pending, num_returns=len(pending), timeout=0.25
                    )
                    for round_ in executor.drain_reports():
                        last_metrics = self._process_round(
                            round_, ckpt_mgr, metrics_history
                        )
                    if done:
                        # surface worker exceptions immediately
                        ray_tpu.get(done)
                for round_ in executor.drain_reports():
                    last_metrics = self._process_round(
                        round_, ckpt_mgr, metrics_history
                    )
                error = None
                break
            except Exception as e:  # worker/actor failure
                error = e
                if attempts_left > 0:
                    time.sleep(0.2)  # backoff before group restart
                    continue
            finally:
                executor.shutdown()

        if error is not None and self.run_config.failure_config.fail_fast:
            raise TrainingFailedError(str(error)) from error
        return Result(
            metrics=last_metrics,
            checkpoint=ckpt_mgr.latest,
            path=run_dir,
            error=error,
            metrics_history=metrics_history,
        )

    def _process_round(self, round_, ckpt_mgr: CheckpointManager, history: list):
        rank0 = round_[0]
        metrics = dict(rank0["metrics"])
        metrics["training_iteration"] = rank0["iteration"] + 1
        path = rank0.get("checkpoint_path")
        if path:
            if os.path.isdir(path):  # shared-fs fast path
                ckpt_mgr.register(path, metrics)
            elif rank0.get("checkpoint_ref") is not None:
                import io
                import shutil
                import tarfile
                import tempfile

                data = ray_tpu.get(rank0["checkpoint_ref"])
                tmp = tempfile.mkdtemp(prefix="ray_tpu_ckpt_rx_")
                try:
                    with tarfile.open(fileobj=io.BytesIO(data)) as tar:
                        tar.extractall(tmp, filter="data")
                    ckpt_mgr.register(tmp, metrics)
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)
        history.append(metrics)
        return metrics

    # Tune integration: run as a trainable with per-trial config override.
    def as_trainable(self) -> Callable:
        base = self

        def trainable(config: Dict[str, Any]):
            import copy

            trainer = copy.copy(base)
            merged = dict(base.train_loop_config)
            merged.update(config)
            trainer.train_loop_config = merged
            return trainer

        return trainable
