"""TorchTrainer: torch.distributed (gloo) data-parallel training.

Reference: python/ray/train/torch/torch_trainer.py +
train/torch/config.py (_TorchBackend: rank-0 is MASTER, every worker runs
init_process_group) + train/torch/train_loop_utils.py (prepare_model ->
DDP wrap, prepare_data_loader -> DistributedSampler). The TPU-native
flagship path is JaxTrainer (jax_trainer.py — SPMD inside one program);
this backend exists for torch workloads and uses gloo, the CPU collective
the image ships (NCCL/GPU is out of scope here).
"""

from __future__ import annotations

from typing import List, Optional

import ray_tpu
from ray_tpu.train.backend_executor import Backend
from ray_tpu.train.trainer import DataParallelTrainer
from ray_tpu.train.worker_group import WorkerGroup


class TorchBackend(Backend):
    """Rendezvous: rank-0's host serves a TCP store; every worker joins the
    process group before the training loop starts."""

    def __init__(self, backend: str = "gloo", port: int = 0,
                 timeout_s: float = 120.0):
        self.backend = backend
        # 0 = pick a free port ON RANK-0's HOST at rendezvous (the store
        # binds there, not on the driver; a fixed default would also make
        # two concurrent trainers on one host share a TCP store). Probed
        # then released — the standard racy-but-practical pattern.
        self.port = port
        self.timeout_s = timeout_s

    def on_start(self, worker_group: WorkerGroup, worker_infos: List[dict]):
        master = worker_infos[0]["hostname"]
        world = len(worker_infos)
        if not self.port:
            def _pick_port():
                import socket

                with socket.socket() as s:
                    s.bind(("", 0))
                    return s.getsockname()[1]

            self.port = int(ray_tpu.get(
                worker_group.workers[0].run.remote(_pick_port), timeout=60
            ))
        if world > 1 and len({i["pid"] for i in worker_infos}) < world:
            # local mode runs actors as threads of one process; a process
            # group cannot form (rank 1 would see rank 0's init and bail,
            # deadlocking rank 0's rendezvous). The reference never hits
            # this because its workers are always processes.
            raise RuntimeError(
                "TorchTrainer with num_workers>1 needs cluster mode "
                "(ray_tpu.init(cluster=True) or a real cluster): local "
                "mode workers share one process and torch.distributed "
                "requires one process per rank"
            )

        def _init(master_addr, port, world_size, rank, backend, timeout_s):
            import datetime
            import os
            import socket

            import torch.distributed as dist

            if dist.is_available() and dist.is_initialized():
                return True
            try:
                master_ip = socket.gethostbyname(master_addr)
            except OSError:
                master_ip = master_addr
            if master_ip.startswith("127."):
                # single-host group: gloo would otherwise advertise a
                # non-loopback interface (whatever eth address exists) for
                # peer pairing and hang at connectFullMesh
                os.environ.setdefault("GLOO_SOCKET_IFNAME", "lo")
                os.environ.setdefault("TP_SOCKET_IFNAME", "lo")
            dist.init_process_group(
                backend=backend,
                init_method=f"tcp://{master_addr}:{port}",
                world_size=world_size,
                rank=rank,
                timeout=datetime.timedelta(seconds=timeout_s),
            )
            return True

        futs = [
            w.run.remote(_init, master, self.port, world, rank,
                         self.backend, self.timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(futs, timeout=self.timeout_s + 60)

    def on_shutdown(self, worker_group: WorkerGroup):
        def _destroy():
            import torch.distributed as dist

            if dist.is_available() and dist.is_initialized():
                dist.destroy_process_group()
            return True

        try:
            ray_tpu.get(
                [w.run.remote(_destroy) for w in worker_group.workers],
                timeout=30,
            )
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass

    def worker_env(self, rank: int, worker_infos: List[dict]):
        return {
            "MASTER_ADDR": worker_infos[0]["hostname"],
            "MASTER_PORT": str(self.port),
            "WORLD_SIZE": str(len(worker_infos)),
            "RANK": str(rank),
        }


def prepare_model(model):
    """Wrap in DistributedDataParallel when a multi-worker group is up
    (reference: train_loop_utils.prepare_model; gloo -> CPU DDP)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


class _EpochedLoader:
    """Iterates the sharded loader, bumping sampler.set_epoch each pass so
    shuffle=True draws a fresh permutation per epoch (reference:
    prepare_data_loader's epoch wrapping; without it DistributedSampler
    replays the epoch-0 permutation forever)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0
        self.batch_size = loader.batch_size
        self.dataset = loader.dataset

    def __iter__(self):
        # each pass IS an epoch; manual sampler.set_epoch is unnecessary
        # (and would be overridden here)
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        # delegate everything else (sampler, num_workers, pin_memory, ...)
        # so code written against a real DataLoader keeps working
        return getattr(self._loader, name)


def prepare_data_loader(loader):
    """Re-shard a DataLoader across the group with a DistributedSampler,
    preserving the loader's own ordering choice (reference:
    train_loop_utils.prepare_data_loader). Returns the loader unchanged
    outside a group."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return loader
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    sampler = DistributedSampler(
        loader.dataset,
        num_replicas=dist.get_world_size(),
        rank=dist.get_rank(),
        # keep the user's ordering: only shuffle if their loader did
        shuffle=isinstance(loader.sampler, RandomSampler),
    )
    sharded = DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=loader.collate_fn,
        drop_last=loader.drop_last,
    )
    return _EpochedLoader(sharded, sampler)


class TorchTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *,
                 torch_backend: Optional[TorchBackend] = None, **kwargs):
        kwargs.setdefault("backend", torch_backend or TorchBackend())
        super().__init__(train_loop_per_worker, **kwargs)
