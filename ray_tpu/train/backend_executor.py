"""BackendExecutor: starts the worker group, runs rendezvous, drives the
training loop, and streams back reports.

Reference: python/ray/train/_internal/backend_executor.py
(BackendExecutor.start — spawns WorkerGroup, assigns world/local/node ranks,
sets MASTER_ADDR/PORT and calls the backend's on_start). The TPU-native
backend's "process group" is jax.distributed across hosts; within one host
the mesh lives inside each worker's SPMD program, so rendezvous reduces to
rank assignment + context install.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint, persist_checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.session import make_report_bus
from ray_tpu.train.worker_group import WorkerGroup


def _apply_env(env: Dict[str, str]):
    import os

    os.environ.update({str(k): str(v) for k, v in env.items()})
    return True


class Backend:
    """Hook interface (reference: train/backend/backend.py Backend).
    on_start runs on the driver after worker creation; worker_env(rank)
    values are then exported into each worker's process environment."""

    def on_start(self, worker_group: WorkerGroup, worker_infos: List[dict]):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass

    def worker_env(self, rank: int, worker_infos: List[dict]) -> Dict[str, str]:
        return {}


class BackendExecutor:
    def __init__(
        self,
        scaling_config: ScalingConfig,
        backend: Optional[Backend] = None,
        experiment_name: str = "default",
        trial_name: str = "trial",
        trial_dir: str = "",
        barrier_timeout_s: float = 600.0,
    ):
        self.scaling = scaling_config
        self.backend = backend or Backend()
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.trial_dir = trial_dir
        self.barrier_timeout_s = barrier_timeout_s
        self.worker_group: Optional[WorkerGroup] = None
        self.bus = None
        self.worker_infos: List[dict] = []

    def start(self, start_checkpoint: Optional[Checkpoint] = None,
              trial_config: Optional[dict] = None):
        n = self.scaling.num_workers
        self.worker_group = WorkerGroup(
            n,
            self.scaling._worker_resources(),
            placement_strategy=self.scaling.placement_strategy,
        )
        self.bus = make_report_bus(n, self.barrier_timeout_s)
        self.worker_infos = self.worker_group.execute("node_info")
        # local/node rank assignment: group by node, order by world rank
        # (reference: backend_executor _create_rank_world_size_mappings)
        per_node: Dict[str, int] = defaultdict(int)
        node_order: Dict[str, int] = {}
        setups = []
        for rank, info in enumerate(self.worker_infos):
            node = info["node_id"]
            if node not in node_order:
                node_order[node] = len(node_order)
            ctx = dict(
                world_size=n,
                world_rank=rank,
                local_rank=per_node[node],
                node_rank=node_order[node],
                experiment_name=self.experiment_name,
                trial_name=self.trial_name,
                trial_dir=self.trial_dir,
                trial_config=dict(trial_config or {}),
            )
            per_node[node] += 1
            setups.append(
                self.worker_group.workers[rank].setup_session.remote(
                    ctx, self.bus,
                    start_checkpoint.path if start_checkpoint else None,
                )
            )
        ray_tpu.get(setups)
        self.backend.on_start(self.worker_group, self.worker_infos)
        # publish backend env vars into the worker processes AFTER on_start
        # (rendezvous may pick ports on_start needs to know first); user
        # loops then see e.g. the torch RANK/WORLD_SIZE/MASTER_* contract
        import os as _os
        import socket as _socket

        driver_ident = (_socket.gethostname(), _os.getpid())
        envs = [
            self.backend.worker_env(rank, self.worker_infos)
            for rank in range(n)
        ]
        # apply only to workers in their OWN processes: local-mode workers
        # are threads of this process, where per-rank env would clobber the
        # driver's environment (and each other, last-rank-wins). Identity is
        # (hostname, pid) — a bare pid can collide with the driver's on a
        # different host.
        calls = [
            w.run.remote(_apply_env, env)
            for w, env, info in zip(
                self.worker_group.workers, envs, self.worker_infos
            )
            if env
            and (info.get("hostname"), info.get("pid")) != driver_ident
        ]
        if calls:
            ray_tpu.get(calls)

    def run_training(self, train_loop: Callable, config: Optional[dict]):
        """Kick off the loop on every worker; returns the per-worker futures."""
        return self.worker_group.execute_async(
            "run_train_loop", train_loop, config
        )

    def drain_reports(self) -> List[List[dict]]:
        """Raises if the bus died — surfaced to the trainer's failure
        handling rather than silently dropping metrics."""
        if self.bus is None:
            return []
        return ray_tpu.get(self.bus.drain.remote(), timeout=30.0)

    def shutdown(self, graceful: bool = True):
        if self.bus is not None:
            try:
                # synchronous abort first: wakes ranks blocked in the push
                # barrier before the actor is torn down
                ray_tpu.get(self.bus.abort.remote(), timeout=5.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(self.bus)
            except Exception:
                pass
            self.bus = None
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
