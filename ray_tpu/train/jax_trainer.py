"""JaxTrainer: the flagship trainer — SPMD training over a device mesh.

Reference analog: python/ray/train/torch/torch_trainer.py (TorchTrainer).
Where TorchTrainer rendezvouses torch.distributed NCCL process groups, the
JaxBackend's job is jax.distributed coordination across *hosts*; within a
host all parallelism (dp/tp/pp/sp) is compiled into the worker's program via
shardings (ray_tpu.parallel), so a single-host JaxTrainer typically runs ONE
worker owning the whole mesh.
"""

from __future__ import annotations

import socket
from typing import List, Optional

from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend_executor import Backend
from ray_tpu.train.trainer import DataParallelTrainer
from ray_tpu.train.worker_group import WorkerGroup


class JaxBackend(Backend):
    """Multi-host rendezvous: pick rank-0's host as coordinator and call
    jax.distributed.initialize on every worker (reference analog:
    _TorchBackend.on_start setting MASTER_ADDR/PORT then
    init_process_group)."""

    def __init__(self, coordinator_port: int = 7621,
                 distributed: Optional[bool] = None):
        self.coordinator_port = coordinator_port
        # None = auto: only initialize jax.distributed when workers span
        # multiple nodes (single-node SPMD needs no host coordination).
        self.distributed = distributed

    def on_start(self, worker_group: WorkerGroup, worker_infos: List[dict]):
        nodes = {info["node_id"] for info in worker_infos}
        dist = self.distributed
        if dist is None:
            dist = len(nodes) > 1
        if not dist:
            return
        coord = f"{worker_infos[0]['hostname']}:{self.coordinator_port}"
        n = len(worker_infos)

        def _init_dist(coord_addr, num_procs, rank):
            import jax

            jax.distributed.initialize(
                coordinate_address=coord_addr,
                num_processes=num_procs,
                process_id=rank,
            )
            return True

        futs = [
            w.run.remote(_init_dist, coord, n, rank)
            for rank, w in enumerate(worker_group.workers)
        ]
        import ray_tpu

        ray_tpu.get(futs)


class JaxTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *, jax_backend: Optional[JaxBackend] = None,
                 **kwargs):
        kwargs.setdefault("backend", jax_backend or JaxBackend())
        super().__init__(train_loop_per_worker, **kwargs)
