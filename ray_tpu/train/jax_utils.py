"""JAX-side training utilities: mesh preparation and pytree checkpointing.

Reference analog: python/ray/train/torch/train_loop_utils.py
(prepare_model/prepare_data_loader wrap torch DDP + CUDA placement). The
TPU-native equivalents operate on meshes and pytrees instead: the worker's
"DDP wrap" is a sharding annotation, and gradient sync is compiled into the
SPMD program by XLA — there is no runtime hook to install.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


def prepare_mesh(axis_names: Sequence[str] = ("dp",),
                 axis_sizes: Optional[Sequence[int]] = None):
    """Build a Mesh over this worker's visible devices.

    Single-host: all local devices. Multi-host (after
    jax.distributed.initialize by JaxBackend): jax.devices() is global, so
    the same call yields the cluster mesh — identical worker code either way,
    which is the point of SPMD.
    """
    import jax
    from ray_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if axis_sizes is None:
        sizes = [1] * len(axis_names)
        sizes[0] = len(devices)
        axis_sizes = sizes
    return make_mesh(tuple(axis_names), sizes=tuple(axis_sizes), devices=devices)


def prepare_data_shard(array, mesh, axis: str = "dp"):
    """Shard a host batch over the mesh's data axis (the analog of the
    reference's DistributedSampler: each rank sees its slice, but here the
    slicing is a device_put with a sharding, zero host-side bookkeeping)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * array.ndim
    spec[0] = axis
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))


# ----------------------------------------------------------- pytree ckpts

_TREE_FILE = "pytree_structure.pkl"
_ARRS_FILE = "pytree_leaves.npz"


def save_pytree(tree: Any, directory: str) -> Checkpoint:
    """Write a jax/numpy pytree as npz + treedef; host-side, device-agnostic."""
    import jax

    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    host_leaves = [np.asarray(leaf) for leaf in leaves]
    np.savez(
        os.path.join(directory, _ARRS_FILE),
        **{f"leaf_{i}": a for i, a in enumerate(host_leaves)},
    )
    with open(os.path.join(directory, _TREE_FILE), "wb") as f:
        pickle.dump(treedef, f)
    return Checkpoint.from_directory(directory)


def load_pytree(checkpoint: Checkpoint) -> Any:
    import jax

    with checkpoint.as_directory() as d:
        with open(os.path.join(d, _TREE_FILE), "rb") as f:
            treedef = pickle.load(f)
        data = np.load(os.path.join(d, _ARRS_FILE))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)
