"""WorkerGroup: the gang of training-worker actors.

Reference: python/ray/train/_internal/worker_group.py (WorkerGroup) —
spawns N actors (optionally inside a placement group), broadcasts callables,
gathers results, tears down.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class _TrainWorker:
    """One rank. Holds the installed session between calls (reference:
    train/_internal/worker_group.py RayTrainWorker)."""

    def __init__(self):
        self._ctx = None

    def node_info(self) -> Dict[str, Any]:
        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": getattr(ctx, "node_id", "local"),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
        }

    def setup_session(self, ctx_dict: dict, bus, start_checkpoint_path):
        from ray_tpu.train.session import TrainContext, _install_session

        self._ctx = TrainContext(**ctx_dict)
        _install_session(self._ctx, bus, start_checkpoint_path)
        return True

    def run(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def run_train_loop(self, train_loop: Callable, config: Optional[dict]):
        import inspect

        sig = inspect.signature(train_loop)
        if len(sig.parameters) == 0:
            return train_loop()
        return train_loop(config or {})

    def shutdown_session(self):
        from ray_tpu.train.session import _clear_session

        _clear_session()
        return True


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        use_placement_group: bool = True,
    ):
        self.num_workers = num_workers
        self._pg: Optional[PlacementGroup] = None
        worker_cls = _TrainWorker
        if use_placement_group and num_workers > 0:
            self._pg = placement_group(
                [dict(resources_per_worker) for _ in range(num_workers)],
                strategy=placement_strategy,
            )
            self._pg.ready(timeout=120.0)
        self.workers = []
        for i in range(num_workers):
            opts: Dict[str, Any] = {
                "num_cpus": resources_per_worker.get("CPU", 1.0),
                "name": f"train_worker_{i}",
            }
            extra = {
                k: v for k, v in resources_per_worker.items()
                if k not in ("CPU", "GPU", "TPU")
            }
            if extra:
                opts["resources"] = extra
            if resources_per_worker.get("TPU"):
                opts["num_tpus"] = resources_per_worker["TPU"]
            if resources_per_worker.get("GPU"):
                opts["num_gpus"] = resources_per_worker["GPU"]
            if self._pg is not None:
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=self._pg, placement_group_bundle_index=i
                )
            self.workers.append(worker_cls.options(**opts).remote())

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs) for w in self.workers]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(method, *args, **kwargs))

    def execute_single(self, rank: int, method: str, *args, **kwargs):
        return ray_tpu.get(
            getattr(self.workers[rank], method).remote(*args, **kwargs)
        )

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
