"""Vectorized resource model: struct-of-arrays cluster resource views.

Reference equivalents:
- NodeResources / ResourceRequest: src/ray/common/scheduling/cluster_resource_data.h
- string->int resource-ID interning: src/ray/common/scheduling/scheduling_ids.h

The reference stores per-node resource maps and iterates them per scheduling
decision. Here the cluster view is a pair of float32 matrices
``total[N, R]`` / ``available[N, R]`` with resource names interned to fixed
column indices, so feasibility and scoring are elementwise array ops that lower
to the TPU VPU/MXU without reshapes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

# Predefined resource columns, mirroring the reference's PredefinedResources
# enum (src/ray/common/scheduling/scheduling_ids.h: CPU/MEM/GPU/OBJECT_STORE_MEM).
# "TPU" is first-class here, where the reference models accelerators as "GPU"
# plus accelerator-type custom resources.
PREDEFINED_RESOURCES: tuple = ("CPU", "GPU", "TPU", "memory", "object_store_memory")

# Feasibility tolerance: resource quantities in the reference are fixed-point
# (FixedPoint, 1e-4 granularity); we use float32 + epsilon.
EPS = 1e-4


class ResourceSpace:
    """Interns resource names to column indices in a fixed-width float32 space.

    The width is padded up front (default 16 columns) so adding a custom
    resource never changes array shapes under jit — mirroring the reference's
    int-interned resource IDs (scheduling_ids.h) but with a static bound, which
    is what XLA needs for stable compiled shapes.
    """

    def __init__(self, max_resources: int = 16):
        if max_resources < len(PREDEFINED_RESOURCES):
            raise ValueError("max_resources must cover predefined resources")
        self.max_resources = max_resources
        self._name_to_idx: Dict[str, int] = {
            name: i for i, name in enumerate(PREDEFINED_RESOURCES)
        }
        self._idx_to_name: List[str] = list(PREDEFINED_RESOURCES)
        self._lock = threading.Lock()

    @property
    def names(self) -> List[str]:
        return list(self._idx_to_name)

    def intern(self, name: str) -> int:
        with self._lock:
            idx = self._name_to_idx.get(name)
            if idx is None:
                idx = len(self._idx_to_name)
                if idx >= self.max_resources:
                    raise ValueError(
                        f"resource space exhausted ({self.max_resources} columns); "
                        f"raise max_resources"
                    )
                self._name_to_idx[name] = idx
                self._idx_to_name.append(name)
            return idx

    def index(self, name: str) -> Optional[int]:
        return self._name_to_idx.get(name)

    def vector(self, resources: Mapping[str, float]) -> np.ndarray:
        """Pack a {name: amount} map into a padded float32 demand vector."""
        v = np.zeros(self.max_resources, dtype=np.float32)
        for name, amount in resources.items():
            if amount == 0:
                continue
            v[self.intern(name)] = float(amount)
        return v

    def unvector(self, vec: np.ndarray) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for i, val in enumerate(np.asarray(vec)):
            if val != 0 and i < len(self._idx_to_name):
                out[self._idx_to_name[i]] = float(val)
        return out


def pack_demands(
    space: ResourceSpace, demands: Sequence[Mapping[str, float]]
) -> np.ndarray:
    """Pack a list of per-task resource maps into a [T, R] demand matrix."""
    out = np.zeros((len(demands), space.max_resources), dtype=np.float32)
    for t, d in enumerate(demands):
        out[t] = space.vector(d)
    return out


@dataclass
class NodeResourceState:
    """Mutable cluster resource view: the scheduler's input matrices.

    Reference: ClusterResourceManager's map of NodeResources
    (src/ray/raylet/scheduling/cluster_resource_manager.cc), flattened to
    struct-of-arrays. Row order is stable; node 0 is conventionally the local
    node so "prefer local" tiebreaks fall out of stable argmin.
    """

    space: ResourceSpace
    node_ids: List[str] = field(default_factory=list)
    total: np.ndarray = None  # [N, R] float32
    available: np.ndarray = None  # [N, R] float32
    alive: np.ndarray = None  # [N] bool
    # [N] bool: live daemons marked unschedulable (graceful drain). A
    # draining row reads alive=False so every kernel/allocation path
    # masks it out with zero new code, but release() still credits it —
    # running tasks bleed off normally instead of leaking debits.
    draining: np.ndarray = None
    labels: List[Dict[str, str]] = field(default_factory=list)

    def __post_init__(self):
        r = self.space.max_resources
        if self.total is None:
            self.total = np.zeros((0, r), dtype=np.float32)
        if self.available is None:
            self.available = np.zeros((0, r), dtype=np.float32)
        if self.alive is None:
            self.alive = np.zeros((0,), dtype=bool)
        if self.draining is None:
            self.draining = np.zeros((0,), dtype=bool)
        self._index: Dict[str, int] = {nid: i for i, nid in enumerate(self.node_ids)}
        # Row indices whose availability changed since the last consume_dirty()
        # — the incremental-upload feed for device-resident scheduler views
        # (kernel_jax.JaxScheduler.update_rows). Mirrors the role of the
        # reference's resource-sync deltas (ray_syncer.cc): ship only what
        # changed, not the whole cluster view, every round.
        self.dirty_rows: set = set()
        # Opt-in availability DELTA log (enable_delta_log): accumulates
        # (new - old) per mutation so a device view that is mid-pipeline
        # (holding in-flight debits the host hasn't applied yet) can be
        # updated INCREMENTALLY — absolute row uploads would erase those
        # debits. Consumers: HybridPolicy.schedule_pipelined ->
        # JaxScheduler.apply_delta. Disabled by default: zero overhead for
        # every other user of this class.
        self._delta_enabled = False
        self._delta_log: Optional[np.ndarray] = None
        # bumped on any node add/remove/revive: O(1) topology identity for
        # per-round cache keys (serializing total/alive with tobytes() at
        # 10k nodes costs ~640KB of memcpy per check)
        self.topology_version = 0

    def enable_delta_log(self) -> None:
        self._delta_enabled = True

    def _log_delta(self, idx: int, applied: np.ndarray) -> None:
        if not self._delta_enabled:
            return
        if (
            self._delta_log is None
            or self._delta_log.shape != self.available.shape
        ):
            old = self._delta_log
            self._delta_log = np.zeros_like(self.available)
            if old is not None and old.size:
                self._delta_log[: old.shape[0]] = old
        self._delta_log[idx] += applied

    def consume_delta(self) -> Optional[np.ndarray]:
        """Return-and-clear the accumulated availability delta matrix, or
        None when nothing changed since the last consume."""
        if self._delta_log is None:
            return None
        out = self._delta_log
        self._delta_log = None
        return out if out.any() else None

    def __len__(self) -> int:
        return len(self.node_ids)

    def node_index(self, node_id: str) -> Optional[int]:
        return self._index.get(node_id)

    def add_node(
        self,
        node_id: str,
        resources: Mapping[str, float],
        labels: Optional[Dict[str, str]] = None,
    ) -> int:
        if node_id in self._index:
            raise ValueError(f"duplicate node {node_id}")
        vec = self.space.vector(resources)
        self.total = np.vstack([self.total, vec[None, :]])
        self.available = np.vstack([self.available, vec[None, :]])
        self.alive = np.append(self.alive, True)
        self.draining = np.append(self.draining, False)
        idx = len(self.node_ids)
        self.node_ids.append(node_id)
        self.labels.append(dict(labels or {}))
        self._index[node_id] = idx
        self.topology_version += 1
        return idx

    def remove_node(self, node_id: str) -> None:
        idx = self._index.get(node_id)
        if idx is None:
            return
        # Keep row (stable indices for in-flight decisions); mark dead and zero
        # availability so the kernels mask it out — same effect as the
        # reference erasing the node from the cluster view.
        self.alive[idx] = False
        self.draining[idx] = False
        self.available[idx] = 0.0
        self.total[idx] = 0.0
        self.topology_version += 1

    def revive_node(self, node_id: str, resources: Mapping[str, float]) -> None:
        """Bring a dead row back (a daemon re-registered with the same id)."""
        idx = self._index[node_id]
        vec = self.space.vector(resources)
        self.total[idx] = vec
        self.available[idx] = vec.copy()
        self.alive[idx] = True
        self.draining[idx] = False
        self.topology_version += 1

    def drain_node(self, node_id: str) -> None:
        """Mark a LIVE node unschedulable (graceful drain): kernels and
        allocate() see alive=False so nothing new lands, but the row's
        capacity/debits are preserved and release() keeps crediting it —
        running tasks bleed off instead of being killed."""
        idx = self._index.get(node_id)
        if idx is None or self.draining[idx]:
            return
        self.draining[idx] = True
        self.alive[idx] = False
        self.topology_version += 1

    def undrain_node(self, node_id: str) -> None:
        """Cancel a drain (demand returned before the terminate)."""
        idx = self._index.get(node_id)
        if idx is None or not self.draining[idx]:
            return
        self.draining[idx] = False
        self.alive[idx] = True
        self.topology_version += 1

    def update_available(self, node_id: str, available: Mapping[str, float]) -> None:
        """Overwrite a node's availability from a sync report (ray_syncer-style)."""
        idx = self._index[node_id]
        old = self.available[idx].copy() if self._delta_enabled else None
        self.available[idx] = self.space.vector(available)
        if old is not None:
            self._log_delta(idx, self.available[idx] - old)
        self.dirty_rows.add(idx)

    def allocate(self, node_idx: int, demand: np.ndarray) -> bool:
        """Try to deduct `demand` from node `node_idx`. Returns False if it no
        longer fits (the caller treats that as a failed lease → reschedule)."""
        if not self.alive[node_idx]:
            return False
        if np.any(self.available[node_idx] + EPS < demand):
            return False
        old = self.available[node_idx].copy() if self._delta_enabled else None
        self.available[node_idx] -= demand
        np.maximum(self.available[node_idx], 0.0, out=self.available[node_idx])
        if old is not None:
            self._log_delta(int(node_idx), self.available[node_idx] - old)
        self.dirty_rows.add(int(node_idx))
        return True

    def release(self, node_idx: int, demand: np.ndarray) -> None:
        if not self.alive[node_idx] and not self.draining[node_idx]:
            return
        old = self.available[node_idx].copy() if self._delta_enabled else None
        self.available[node_idx] = np.minimum(
            self.available[node_idx] + demand, self.total[node_idx]
        )
        if old is not None:
            self._log_delta(int(node_idx), self.available[node_idx] - old)
        self.dirty_rows.add(int(node_idx))

    def replace_available(self, new_avail: np.ndarray) -> None:
        """Wholesale availability swap (bundle packing returns a full new
        matrix) that keeps the dirty-row contract: every changed row is
        marked so device-view consumers stay in sync."""
        changed = np.flatnonzero((self.available != new_avail).any(axis=1))
        if self._delta_enabled:
            for i in changed:
                self._log_delta(int(i), new_avail[i] - self.available[i])
        self.dirty_rows.update(int(i) for i in changed)
        self.available = new_avail

    def consume_dirty(self) -> List[int]:
        """Return-and-clear the changed row indices (sorted). The device view
        consumer uploads exactly these rows, then the set starts fresh."""
        out = sorted(self.dirty_rows)
        self.dirty_rows.clear()
        return out

    def feasible_anywhere(self, demand: np.ndarray) -> bool:
        """Is there any node whose *total* resources cover the demand?
        (Reference: ClusterResourceScheduler::IsSchedulableOnNode on totals —
        infeasible-forever vs just-currently-full.)"""
        if len(self.node_ids) == 0:
            return False
        ok = np.all(self.total + EPS >= demand[None, :], axis=1) & self.alive
        return bool(ok.any())

    def snapshot(self) -> "NodeResourceState":
        s = NodeResourceState(
            space=self.space,
            node_ids=list(self.node_ids),
            total=self.total.copy(),
            available=self.available.copy(),
            alive=self.alive.copy(),
            draining=self.draining.copy(),
            labels=[dict(l) for l in self.labels],
        )
        return s

    def available_map(self) -> Dict[str, Dict[str, float]]:
        return {
            nid: self.space.unvector(self.available[i])
            for i, nid in enumerate(self.node_ids)
            if self.alive[i]
        }

    def total_map(self) -> Dict[str, Dict[str, float]]:
        return {
            nid: self.space.unvector(self.total[i])
            for i, nid in enumerate(self.node_ids)
            if self.alive[i]
        }
