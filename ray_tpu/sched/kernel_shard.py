"""Node-axis-sharded scheduling kernel: the kernel under shard_map.

The north star (BASELINE.json config 5) names "vectorized bin-packing
... under pmap": design-for-N means the cluster matrix itself shards
over a device mesh, not just fits one chip. Here the NODE axis of
avail/total/alive splits into contiguous blocks across the mesh's
``nodes`` axis (jax shards axis 0 contiguously); each device runs the
same per-class pass as `kernel_jax.schedule_classes` over its block, and
the few cross-block quantities ride collectives:

  - feasible-node counts / placed totals: `psum` scalars;
  - the (score-bucket, node-index) prefix order of the fill: per-shard
    bucket totals are `all_gather`-ed, then shard- and bucket-level
    exclusive prefixes recompose the GLOBAL prefix each local node sees.

Decision equality with the single-device kernel is exact, not
approximate: contiguous shard blocks preserve node order, saturating
partial sums clamp at the same SAT=2**23 (any saturated component already
exceeds every legal `remaining`, so take=0 on both sides; unsaturated
prefixes are exact in float32) — golden-tested against
`schedule_classes` on the virtual 8-device CPU mesh
(tests/test_sched_shard.py).

Reference anchor: the reference scales scheduling by sharding WORK over
raylets (each ClusterTaskManager sees the whole cluster view); here the
VIEW shards over chips and one program schedules the whole queue —
ICI collectives instead of ray_syncer broadcasts.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.sched.kernel_jax import (
    EPS,
    INF_FIT,
    MAX_PASSES,
    SAT,
    SCORE_BUCKETS,
    _class_fit,
    _score_bucket,
    _sat_cumsum,
    _threshold_cap,
    critical_util,
)


def _fill_by_bucket_sharded(cap, bucket, remaining, axis_name):
    """Global (bucket, node) prefix fill where this device holds one
    contiguous node block. Mirrors kernel_jax._fill_by_bucket with the
    prefix decomposed as
        global_prev = bucket_offset(global) + shard_prefix(bucket)
                      + within_shard_exclusive
    every component saturated at SAT (pairwise, so each float32 add stays
    on exact integers <= 2*SAT)."""
    n_buckets = SCORE_BUCKETS
    capf = jnp.minimum(cap, remaining).astype(jnp.float32)
    onehot = (
        bucket[None, :] == jnp.arange(n_buckets)[:, None]
    ).astype(jnp.float32)
    contrib = onehot * capf[None, :]  # [B, Nlocal]
    shifted = jnp.concatenate(
        [jnp.zeros((n_buckets, 1), jnp.float32), contrib[:, :-1]], axis=1
    )
    within_excl = _sat_cumsum(shifted, axis=1)  # [B, Nlocal]
    local_tot = jnp.minimum(
        within_excl[:, -1] + contrib[:, -1], jnp.float32(SAT)
    )  # [B]
    all_tot = jax.lax.all_gather(local_tot, axis_name)  # [p, B]
    shard_scan = _sat_cumsum(all_tot, axis=0)  # [p, B] inclusive
    idx = jax.lax.axis_index(axis_name)
    shard_prefix = jnp.where(
        idx > 0,
        jnp.take(shard_scan, jnp.maximum(idx - 1, 0), axis=0),
        jnp.zeros((n_buckets,), jnp.float32),
    )  # [B] total of this bucket on earlier shards
    bucket_tot = shard_scan[-1]  # [B] global per-bucket totals (saturated)
    bucket_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.float32), _sat_cumsum(bucket_tot, axis=0)[:-1]]
    )
    base = jnp.minimum(bucket_off + shard_prefix, jnp.float32(SAT))  # [B]
    prev_mat = base[:, None] + within_excl  # each term <= SAT: exact adds
    prev = (prev_mat * onehot).sum(axis=0)  # [Nlocal]
    take = jnp.clip(jnp.float32(remaining) - prev, 0.0, capf)
    return take.astype(jnp.int32)


def _one_class_sharded(avail, total, alive, d, count, thr, max_passes,
                       axis_name):
    Nl = avail.shape[0]

    def cond(state):
        _, remaining, _, p, stalled = state
        return (remaining > 0) & (p < max_passes) & (~stalled)

    def body(state):
        avail, remaining, acc, p, _ = state
        fit = _class_fit(avail, alive, d)
        n_feasible = jax.lax.psum((fit > 0).sum(), axis_name)
        util = critical_util(avail, total)
        bucket = _score_bucket(util, thr)
        cap_thresh = _threshold_cap(avail, total, d, thr)
        equal_share = (
            remaining + jnp.maximum(n_feasible, 1) - 1
        ) // jnp.maximum(n_feasible, 1)
        cap = jnp.where(
            util < thr, cap_thresh, equal_share.astype(jnp.int32)
        )
        cap = jnp.minimum(jnp.minimum(cap, fit), remaining)
        take = _fill_by_bucket_sharded(cap, bucket, remaining, axis_name)
        got = jax.lax.psum(take.sum(), axis_name)
        avail = jnp.maximum(
            avail - take[:, None].astype(jnp.float32) * d[None, :], 0.0
        )
        stalled = (got == 0) | (n_feasible == 0)
        return (avail, remaining - got, acc + take, p + 1, stalled)

    # acc derives from avail so shard_map types it as per-shard VARYING
    # (a plain zeros() would be replicated-typed and fail the while_loop
    # carry check)
    acc0 = (avail[:, 0] * 0.0).astype(jnp.int32)
    init = (avail, count, acc0, jnp.int32(0), False)
    avail, _, acc, _, _ = jax.lax.while_loop(cond, body, init)
    return avail, acc


def _sharded_body(avail, total, alive, demands, counts, thr, max_passes,
                  axis_name):
    def step(avail, xs):
        d, count = xs
        avail, acc = _one_class_sharded(
            avail, total, alive, d, count, thr, max_passes, axis_name
        )
        return avail, acc

    new_avail, assigned = jax.lax.scan(
        step, avail.astype(jnp.float32), (demands, counts)
    )
    return assigned, new_avail


def make_sharded_scheduler(mesh: Mesh, axis: str = "nodes",
                           max_passes: int = MAX_PASSES):
    """Build a jitted sharded kernel over `mesh`'s `axis`.

    Returns fn(avail [N,R], total [N,R], alive [N], demands [C,R],
    counts [C], thr) -> (assigned [C,N] int32, new_avail [N,R]); N must
    divide by the axis size; inputs may be host arrays (jit shards them
    per the in_shardings)."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    node_sharded = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    def _block(avail, total, alive, demands, counts, thr):
        return _sharded_body(
            avail, total, alive, demands, counts, thr, max_passes, axis
        )

    body = shard_map(
        _block,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P(), P()),
        out_specs=(P(None, axis), P(axis)),
    )

    @functools.partial(
        jax.jit,
        in_shardings=(node_sharded, node_sharded, node_sharded,
                      replicated, replicated, replicated),
        out_shardings=(replicated, node_sharded),
    )
    def run(avail, total, alive, demands, counts, thr):
        return body(avail, total, alive, demands, counts, thr)

    def fn(avail, total, alive, demands, counts,
           thr=0.5) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return run(
            jnp.asarray(avail, jnp.float32),
            jnp.asarray(total, jnp.float32),
            jnp.asarray(alive),
            jnp.asarray(demands, jnp.float32),
            jnp.asarray(counts, jnp.int32),
            jnp.float32(thr),
        )

    return fn
