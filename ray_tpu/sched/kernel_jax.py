"""JAX twin of the NumPy scheduler kernels — the `policy="jax_tpu"` path.

Implements *identical math* to `kernel_np.schedule_classes` under `jax.jit`
so a whole pending queue is placed in one compiled TPU program: feasibility
masks and utilization scores are elementwise [N, R] ops (VPU), the per-class
pass is a `lax.while_loop`, and the class dimension is a `lax.scan` — no
data-dependent Python control flow, static shapes throughout (classes/nodes
are padded by the caller via `pad_problem`).

Decision equality with the NumPy kernel is golden-tested
(tests/test_sched_kernel.py), mirroring the reference's pure-function
scheduler tests (src/ray/raylet/scheduling/cluster_resource_scheduler_test.cc).

Numerical note: prefix sums in the score-ordered fill are computed in float32;
partial sums are exact below 2**24, so per-class pending counts must stay
under 2**24 (asserted host-side). Class counts larger than that should be
split by the caller — the driver loop schedules in rounds anyway.

Backend note: decision equality with the NumPy twins is exact on the CPU
backend (where the golden tests run, and where the jax_tpu policy's
small-round path computes). On TPU HARDWARE, XLA's fast division
(reciprocal-multiply, not correctly rounded) can shift a fit count by one
at exact-capacity boundaries — measured at ~2% of random problems with a
few +-1/+2 cells each (300-seed sweep, 2026-07-30). The invariants that
matter survive: assigned counts never exceed per-class demand, placements
never exceed availability (0 violations in the same sweep; bench.py
asserts both on every TPU run), and the makespan-gap numbers in BENCH
are measured WITH TPU numerics, so quality claims already include the
effect.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-4
INF_FIT = jnp.int32(2**30)
DEFAULT_SPREAD_THRESHOLD = 0.5
MAX_PASSES = 8
_MAX_CLASS_COUNT = 2**23


def critical_util(avail: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    used = total - avail
    frac = jnp.where(total > 0, used / jnp.maximum(total, EPS), 0.0)
    return frac.max(axis=1).astype(jnp.float32)


def _class_fit(avail, alive, d):
    ratios = jnp.where(
        d[None, :] > 0,
        jnp.floor((avail + EPS) / jnp.maximum(d[None, :], 1e-9)),
        jnp.float32(INF_FIT),
    )
    fit = jnp.clip(ratios.min(axis=1), 0.0, jnp.float32(INF_FIT))
    return jnp.where(alive, fit, 0.0).astype(jnp.int32)


def _threshold_cap(avail, total, d, thr):
    used = total - avail
    head = thr * total - used
    k = jnp.where(
        d[None, :] > 0,
        jnp.floor((head + EPS) / jnp.maximum(d[None, :], 1e-9)),
        jnp.float32(INF_FIT),
    ).min(axis=1)
    k = jnp.clip(k, 0.0, jnp.float32(INF_FIT) - 1.0)
    return (k + 1.0).astype(jnp.int32)


SCORE_BUCKETS = 64


def _score_bucket(util, thr, n_buckets=SCORE_BUCKETS):
    over = (util - thr) / jnp.maximum(1e-6, 1.0 - thr)
    over = jnp.clip(over, 0.0, 1.0)
    b = jnp.where(util >= thr, 1.0 + jnp.floor(over * (n_buckets - 2)), 0.0)
    return jnp.clip(b, 0, n_buckets - 1).astype(jnp.int32)


def _fill_by_bucket(cap, bucket, remaining, n_buckets=SCORE_BUCKETS):
    """Sort-free prefix fill: take from nodes in (score bucket, node index)
    order until `remaining` is exhausted. The sort becomes a one-hot masked
    cumsum — [N, B] elementwise + scans, no argsort on the hot path.
    Exactly equal to stable-argsort-by-bucket (kernel_np._fill_by_score on
    bucket keys); float32 prefix sums are exact below 2**24 (asserted by
    pad_problem)."""
    capf = jnp.minimum(cap, remaining).astype(jnp.float32)
    # [B, N] layout: the long node axis is the minor (lane) dimension, so the
    # cumsum runs along lanes instead of sublanes.
    onehot = (bucket[None, :] == jnp.arange(n_buckets)[:, None]).astype(jnp.float32)
    contrib = onehot * capf[None, :]  # [B, N]
    # Saturating associative scans, NOT jnp.cumsum: XLA lowers cumsum to a
    # quadratic reduce-window on TPU (profiled at 72 of 89 ms/round at
    # N=10240 — 81% of the whole scan kernel). The EXCLUSIVE prefix must be
    # scanned directly over a shifted input — subtracting contrib from a
    # saturated inclusive scan is unsound (SAT - contrib can fall back under
    # `remaining`). With both prefix components saturated at SAT=2**23 and
    # remaining < 2**23 (pad_problem's assert): any saturated component
    # forces prev >= SAT > remaining => take=0, and wherever the true
    # prefix < remaining nothing saturated, so partials are exact in f32 —
    # decisions stay bit-identical to the NumPy twin's int64 path.
    shifted = jnp.concatenate(
        [jnp.zeros((n_buckets, 1), jnp.float32), contrib[:, :-1]], axis=1
    )
    within_excl = _sat_cumsum(shifted, axis=1)  # exclusive prefix per bucket
    bucket_tot = jnp.minimum(
        within_excl[:, -1] + contrib[:, -1], jnp.float32(SAT)
    )  # [B]
    bucket_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.float32), _sat_cumsum(bucket_tot, axis=0)[:-1]]
    )
    prev_mat = bucket_off[:, None] + within_excl  # each term <= SAT: exact
    prev = (prev_mat * onehot).sum(axis=0)  # [N]
    take = jnp.clip(jnp.float32(remaining) - prev, 0.0, capf)
    return take.astype(jnp.int32)


def _one_class(avail, total, alive, d, count, thr, max_passes):
    N = avail.shape[0]

    def cond(state):
        _, remaining, _, p, stalled = state
        return (remaining > 0) & (p < max_passes) & (~stalled)

    def body(state):
        avail, remaining, acc, p, _ = state
        fit = _class_fit(avail, alive, d)
        n_feasible = (fit > 0).sum()
        util = critical_util(avail, total)
        bucket = _score_bucket(util, thr)
        cap_thresh = _threshold_cap(avail, total, d, thr)
        equal_share = (remaining + jnp.maximum(n_feasible, 1) - 1) // jnp.maximum(
            n_feasible, 1
        )
        cap = jnp.where(util < thr, cap_thresh, equal_share.astype(jnp.int32))
        cap = jnp.minimum(jnp.minimum(cap, fit), remaining)
        take = _fill_by_bucket(cap, bucket, remaining)
        got = take.sum()
        avail = jnp.maximum(avail - take[:, None].astype(jnp.float32) * d[None, :], 0.0)
        stalled = (got == 0) | (n_feasible == 0)
        return (avail, remaining - got, acc + take, p + 1, stalled)

    init = (avail, count, jnp.zeros((N,), jnp.int32), jnp.int32(0), False)
    avail, _, acc, _, _ = jax.lax.while_loop(cond, body, init)
    return avail, acc


@functools.partial(jax.jit, static_argnames=("max_passes",))
def schedule_classes(
    avail: jnp.ndarray,
    total: jnp.ndarray,
    alive: jnp.ndarray,
    demands: jnp.ndarray,
    counts: jnp.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    max_passes: int = MAX_PASSES,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched hybrid placement: identical semantics to kernel_np.schedule_classes.

    Returns (assigned[C, N] int32, new availability [N, R] float32).
    """
    thr = jnp.float32(spread_threshold)

    def step(avail, xs):
        d, count = xs
        avail, acc = _one_class(avail, total, alive, d, count, thr, max_passes)
        return avail, acc

    avail = avail.astype(jnp.float32)
    new_avail, assigned = jax.lax.scan(step, avail, (demands, counts))
    return assigned, new_avail


def _fit_matrix(avail, alive, demands):
    """[C, N] how many tasks of each class fit on each node, without
    materializing [C, N, R]: static unroll over the (padded, small) resource
    dim."""
    C, R = demands.shape
    N = avail.shape[0]
    fit = jnp.full((C, N), jnp.float32(INF_FIT))
    for r in range(R):
        d_r = demands[:, r]
        ratio = jnp.floor(
            (avail[:, r][None, :] + EPS) / jnp.maximum(d_r, 1e-9)[:, None]
        )
        fit = jnp.where(d_r[:, None] > 0, jnp.minimum(fit, ratio), fit)
    fit = jnp.clip(fit, 0.0, jnp.float32(INF_FIT))
    return fit * alive[None, :].astype(jnp.float32)


def _threshold_cap_matrix(avail, total, demands, thr):
    """[C, N] tasks-until-threshold per class/node (+1, matching greedy)."""
    C, R = demands.shape
    N = avail.shape[0]
    used = total - avail
    k = jnp.full((C, N), jnp.float32(INF_FIT))
    for r in range(R):
        d_r = demands[:, r]
        head = thr * total[:, r] - used[:, r]  # [N]
        cap_r = jnp.floor((head[None, :] + EPS) / jnp.maximum(d_r, 1e-9)[:, None])
        k = jnp.where(d_r[:, None] > 0, jnp.minimum(k, cap_r), k)
    return jnp.clip(k, 0.0, jnp.float32(INF_FIT) - 1.0) + 1.0


# Saturation bound for prefix sums: float32 holds integers exactly up to
# 2**24; saturating at 2**23 keeps every partial (<= SAT + element) exact.
SAT = float(1 << 23)


def _sat_cumsum(x, axis):
    """Inclusive saturating prefix sum: result[i] = min(sum(x[:i+1]), SAT).
    min-plus saturating add of nonnegatives is associative, so the parallel
    scan computes exactly the sequential result — which is what makes the
    NumPy twin (plain int64 cumsum clipped at SAT) bit-identical."""
    return jax.lax.associative_scan(
        lambda a, b: jnp.minimum(a + b, jnp.float32(SAT)), x, axis=axis
    )


def _rounds_core(avail, total, alive_f, demands, counts, thr, rounds, active):
    """Two-phase [C, N] matrix placement over `rounds` global rounds — the
    shared core of schedule_classes_rounds (C = whole queue) and
    schedule_classes_chunked (C = one chunk). Pure code motion from the
    original schedule_classes_rounds body; decisions are bit-identical.

    Returns (assigned [C, N] float32, avail [N, R] float32)."""
    C, R = demands.shape
    N = avail.shape[0]
    # compressed views: only the demanded resource columns
    d_act = [demands[:, r] for r in active]  # each [C]

    def fit_matrix(avail):
        fit = jnp.full((C, N), jnp.float32(INF_FIT))
        for j, r in enumerate(active):
            d_r = d_act[j]
            ratio = jnp.floor(
                (avail[:, r][None, :] + EPS) / jnp.maximum(d_r, 1e-9)[:, None]
            )
            fit = jnp.where(d_r[:, None] > 0, jnp.minimum(fit, ratio), fit)
        fit = jnp.clip(fit, 0.0, jnp.float32(INF_FIT))
        return fit * alive_f[None, :]

    def threshold_cap_matrix(avail):
        k = jnp.full((C, N), jnp.float32(INF_FIT))
        for j, r in enumerate(active):
            d_r = d_act[j]
            head = thr * total[:, r] - (total[:, r] - avail[:, r])
            cap_r = jnp.floor((head[None, :] + EPS) / jnp.maximum(d_r, 1e-9)[:, None])
            k = jnp.where(d_r[:, None] > 0, jnp.minimum(k, cap_r), k)
        return jnp.clip(k, 0.0, jnp.float32(INF_FIT) - 1.0) + 1.0

    def claim_phase(avail_p, remaining, cap):
        """cap [C, N] in node-index order; returns take [C, N]."""
        capc = jnp.minimum(cap, jnp.minimum(remaining[:, None], jnp.float32(SAT)))
        prev = _sat_cumsum(capc, axis=1) - capc  # along N (lanes)
        want = jnp.clip(remaining[:, None] - prev, 0.0, capc)
        # class-priority conflict resolution in [N, C] layout so the
        # cumulative-usage scan runs along the minor (lane) axis too
        wantT = want.T  # [N, C]
        takeT = wantT
        for j, r in enumerate(active):
            d_r = d_act[j]
            usage = wantT * d_r[None, :]
            prev_r = _sat_cumsum(usage, axis=1) - usage  # earlier classes
            head = avail_p[:, r][:, None] - prev_r
            fit_r = jnp.floor((head + EPS) / jnp.maximum(d_r, 1e-9)[None, :])
            takeT = jnp.where(
                d_r[None, :] > 0,
                jnp.minimum(takeT, jnp.clip(fit_r, 0.0, jnp.float32(SAT))),
                takeT,
            )
        return jnp.clip(takeT.T, 0.0, want)

    def run_phase(avail, remaining, assigned, cap):
        # Nodes are filled in node-index order (no utilization sort). For
        # phase A this is EXACTLY the old sorted behavior: only bucket-0
        # (under-threshold) nodes have nonzero cap, and stable sort keeps
        # equal keys in index order. For phase B it is a deliberate
        # divergence — the [C, N] permutation gathers the sort required were
        # the kernel's dominant cost on TPU (~100ms of a 146ms round at
        # 10k nodes), and the makespan simulator bounds the quality effect
        # (tests/test_simulator.py, bench configs 1-3). NumPy twin matches.
        take = claim_phase(avail, remaining, cap)
        usage = jnp.einsum("cn,cr->nr", take, demands)
        avail = jnp.maximum(avail - usage, 0.0)
        return avail, remaining - take.sum(axis=1), assigned + take

    def one_round(state, _):
        avail, remaining, assigned = state
        util = critical_util(avail, total)
        under = (util < thr).astype(jnp.float32)[None, :] * alive_f[None, :]
        capA = jnp.minimum(fit_matrix(avail), threshold_cap_matrix(avail))
        avail, remaining, assigned = run_phase(
            avail, remaining, assigned, capA * under
        )
        fit = fit_matrix(avail)
        n_feas = (fit > 0).sum(axis=1).astype(jnp.float32)
        share = jnp.ceil(remaining / jnp.maximum(n_feas, 1.0))
        capB = jnp.minimum(fit, share[:, None])
        avail, remaining, assigned = run_phase(avail, remaining, assigned, capB)
        return (avail, remaining, assigned), None

    remaining = counts.astype(jnp.float32)
    assigned = jnp.zeros((C, N), jnp.float32)
    (avail, remaining, assigned), _ = jax.lax.scan(
        one_round, (avail, remaining, assigned), None, length=rounds
    )
    return assigned, avail


@functools.partial(jax.jit, static_argnames=("rounds", "active_idx"))
def schedule_classes_rounds(
    avail: jnp.ndarray,
    total: jnp.ndarray,
    alive: jnp.ndarray,
    demands: jnp.ndarray,
    counts: jnp.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    rounds: int = 4,
    active_idx: Optional[tuple] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fully-parallel variant of schedule_classes: all classes are placed by
    [C, N] matrix passes instead of a per-class sequential scan (whose
    ~0.4ms/class op latency dominated the 1M-task round).

    Per global round, two phases (A: fill nodes only up to the spread
    threshold; B: equal-share the overflow across feasible nodes). Each phase:
      1. every class prefix-fills its capacity caps in node-index order
         (exact fill via saturating-scan cumsum — no sort, no permutation
         gathers: those dominated the round cost on TPU, and for phase A
         index order IS sorted order since only under-threshold/bucket-0
         nodes have nonzero cap);
      2. conflicts are resolved by class-priority: a class sees the
         *claimed* usage of lower-indexed classes via a saturating cumsum
         over C, and trims its take to the remaining headroom — so the result
         is feasible by construction and close to sequentially scheduling
         classes in index order.

    NumPy twin: kernel_np.schedule_classes_rounds (bit-identical decisions;
    golden-tested). Exactness bounds: per-class counts < 2**23 (asserted in
    pad_problem) and integer-granular demands; fractional or >2**24-magnitude
    resource amounts may diverge between backends by +-1 task at boundaries.

    active_idx: static tuple of resource columns any class actually demands
    (host-computed). The [C, N] passes loop only over those columns — with
    the usual 3-4 live resources that's a 4-5x cut in HBM traffic vs the
    padded 16-wide resource dim. None -> all columns.
    """
    thr = jnp.float32(spread_threshold)
    avail = avail.astype(jnp.float32)
    demands = demands.astype(jnp.float32)
    C, R = demands.shape
    active = tuple(range(R)) if active_idx is None else tuple(active_idx)
    assigned, avail = _rounds_core(
        avail, total, alive.astype(jnp.float32), demands, counts, thr, rounds,
        active,
    )
    return assigned.astype(jnp.int32), avail


@functools.partial(
    jax.jit, static_argnames=("chunk", "rounds", "active_idx")
)
def schedule_classes_chunked(
    avail: jnp.ndarray,
    total: jnp.ndarray,
    alive: jnp.ndarray,
    demands: jnp.ndarray,
    counts: jnp.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    chunk: int = 16,
    rounds: int = 2,
    active_idx: Optional[tuple] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chunked middle ground between the sequential `scan` kernel (256
    dependent steps, one class each) and the fully-parallel `rounds` kernel
    (one step, [C, N] matrices, 4 global rounds): a `lax.scan` over C/chunk
    chunks of `chunk` classes, each placed by `_rounds_core`'s two-phase
    fill with class-priority conflict resolution WITHIN the chunk.

    Why: the scan kernel's cost is 256 x (while_loop pass latency) — almost
    entirely sequential-step overhead at [N, R] sizes too small to fill the
    VPU; the rounds kernel pays for full-width [256, N] matrices 4 times
    over. Chunking cuts sequential depth 16x while keeping slabs at
    [16, N] — and availability still updates *between* chunks, so placement
    quality tracks the sequential kernel far closer than global rounds does
    (most-constrained-first ordering puts the classes that care about
    ordering in the earliest chunks). Quality is bounded by the makespan
    simulator (bench configs 1-3), same as every kernel here.

    NumPy twin: kernel_np.schedule_classes_chunked (golden-tested decision
    equality; integer-granular demands, counts < 2**23 as usual). C must be
    a multiple of `chunk` — pad_problem's buckets (16/64/256/1024/4096) all
    are.
    """
    thr = jnp.float32(spread_threshold)
    avail = avail.astype(jnp.float32)
    demands = demands.astype(jnp.float32)
    C, R = demands.shape
    N = avail.shape[0]
    if C % chunk:
        raise ValueError(f"class dim {C} not a multiple of chunk {chunk}")
    alive_f = alive.astype(jnp.float32)
    active = tuple(range(R)) if active_idx is None else tuple(active_idx)
    dg = demands.reshape(C // chunk, chunk, R)
    kg = counts.reshape(C // chunk, chunk)

    def step(avail, xs):
        d, k = xs
        assigned, avail = _rounds_core(
            avail, total, alive_f, d, k, thr, rounds, active
        )
        return avail, assigned

    avail, assigned = jax.lax.scan(step, avail, (dg, kg))
    return assigned.reshape(C, N).astype(jnp.int32), avail


def pad_problem(
    demands: np.ndarray, counts: np.ndarray, class_pad: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad the class dimension to a fixed bucket size so jit recompiles only on
    bucket growth, not on every queue composition change (static shapes are
    what keep the hot path at one compiled program)."""
    C = demands.shape[0]
    assert C <= class_pad, (C, class_pad)
    if int(counts.max(initial=0)) >= _MAX_CLASS_COUNT:
        raise ValueError("per-class count exceeds 2**23; split into rounds")
    d = np.zeros((class_pad, demands.shape[1]), dtype=np.float32)
    d[:C] = demands
    # Padded classes get an impossible demand so they match nothing.
    d[C:, 0] = np.float32(INF_FIT)
    k = np.zeros((class_pad,), dtype=np.int32)
    k[:C] = counts
    return d, k


def bucket_size(n: int, buckets=(16, 64, 256, 1024, 4096)) -> int:
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(max(n, 1))))


@jax.jit
def _scatter_rows(avail, idx, rows):
    return avail.at[idx].set(rows, mode="drop")


class JaxScheduler:
    """Stateful device-resident wrapper: keeps the cluster view on the TPU and
    amortizes host<->device transfer across scheduling rounds (the transfer
    budget is what makes <50ms rounds possible; see SURVEY §7 hard parts).

    The host pushes *incremental* availability updates; the full view is only
    re-uploaded on topology change (node add/remove).
    """

    def __init__(self, total: np.ndarray, alive: np.ndarray, device=None):
        self.device = device or jax.devices()[0]
        self.total = jax.device_put(jnp.asarray(total, jnp.float32), self.device)
        self.alive = jax.device_put(jnp.asarray(alive), self.device)
        self.avail = self.total * self.alive[:, None].astype(jnp.float32)

    def set_available(self, avail: np.ndarray):
        self.avail = jax.device_put(jnp.asarray(avail, jnp.float32), self.device)

    def apply_delta(self, delta: np.ndarray):
        """avail += delta (negative = allocation), clipped to [0, total]."""
        d = jax.device_put(jnp.asarray(delta, jnp.float32), self.device)
        self.avail = jnp.clip(self.avail + d, 0.0, self.total)

    # row-index buckets: pads the scatter to a few static shapes so jit
    # compiles once per bucket, not once per distinct changed-row count
    _ROW_BUCKETS = (16, 64, 256, 1024, 4096)

    def update_rows(self, idx, rows: np.ndarray):
        """Authoritative per-row refresh: avail[idx] = rows. This is the
        production incremental path — the control plane marks rows dirty as
        tasks finish/release (NodeResourceState.dirty_rows) and only those
        rows cross host->device, instead of the whole [N, R] view per round
        (reference analog: ray_syncer.cc per-node deltas).

        Padded indices point one-past-the-end; scatter mode='drop' discards
        them, keeping shapes static for jit."""
        n = len(idx)
        if n == 0:
            return
        N = int(self.total.shape[0])
        if n >= N:
            self.set_available(rows if len(rows) == N else rows[:N])
            return
        pad = next((b for b in self._ROW_BUCKETS if n <= b), n)
        ii = np.full(pad, N, dtype=np.int32)
        ii[:n] = np.asarray(idx, dtype=np.int32)
        vv = np.zeros((pad, self.total.shape[1]), dtype=np.float32)
        vv[:n] = rows
        self.avail = _scatter_rows(
            self.avail,
            jax.device_put(ii, self.device),
            jax.device_put(vv, self.device),
        )

    def schedule_async(self, demands: np.ndarray, counts: np.ndarray,
                       spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
                       algo: str = "scan") -> dict:
        """Enqueue one scheduling round WITHOUT any host<->device sync.

        The returned handle's device array is narrow-dtyped and its
        device->host copy is STARTED (copy_to_host_async); fetch() later
        blocks only on whatever is still in flight. Chaining K rounds
        through this path costs ~latency/K per round instead of a full
        sync round trip each — the pipelined hot loop the north star's
        <50ms/round clause needs on a tunneled device (measured here:
        67ms per forced round trip vs ~5ms/round for 16 chained
        enqueues)."""
        pad = bucket_size(demands.shape[0])
        d, k = pad_problem(
            np.asarray(demands, np.float32), np.asarray(counts), pad
        )
        if algo in ("rounds", "chunked"):
            active = tuple(int(i) for i in np.flatnonzero((d > 0).any(axis=0)))
            fn = (
                schedule_classes_chunked if algo == "chunked"
                else schedule_classes_rounds
            )
            assigned, new_avail = fn(
                self.avail, self.total, self.alive,
                jnp.asarray(d), jnp.asarray(k), spread_threshold,
                active_idx=active,
            )
        else:
            assigned, new_avail = schedule_classes(
                self.avail, self.total, self.alive,
                jnp.asarray(d), jnp.asarray(k), spread_threshold,
            )
        self.avail = new_avail
        out = assigned[: demands.shape[0]]
        C, N = out.shape
        # Sparse (COO) download when it shrinks the wire payload: the
        # assignment matrix is mostly zeros (placements are bounded by the
        # submitted counts), and on a tunneled device the payload IS the
        # round's wall time. nonzero with a static `size` keeps shapes
        # jit-stable (a few pow-2 cap buckets); padding slots replicate
        # cell (0, 0), whose value is also shipped, so host-side
        # assignment-reconstruction is exactly idempotent.
        cap_needed = int(np.sum(counts, dtype=np.int64))
        cap = next(
            (b for b in self._NONZERO_BUCKETS if b >= cap_needed), None
        )
        m = int(np.max(counts, initial=0))
        if cap is not None and cap * 5 < C * N:
            ci, ni = jnp.nonzero(out, size=cap, fill_value=0)
            vals = out[ci, ni]
            ci = ci.astype(jnp.int16 if C < 32768 else jnp.int32)
            ni = ni.astype(jnp.int16 if N < 32768 else jnp.int32)
            if m < 256:
                vals = vals.astype(jnp.uint8)
            parts = {"ci": ci, "ni": ni, "vals": vals}
            for p in parts.values():
                try:
                    p.copy_to_host_async()
                except AttributeError:
                    pass
            return {"sparse": parts, "shape": (C, N)}
        # dense fallback: narrow purely from HOST knowledge (a class
        # places at most its own count on one node); never sync the
        # device for the exact max
        if m < 256:
            out = out.astype(jnp.uint8)
        elif m < 32768:
            out = out.astype(jnp.int16)
        try:
            out.copy_to_host_async()
        except AttributeError:  # older jax Array without the method
            pass
        return {"out": out}

    # static caps for the sparse-download nonzero program (one compile per
    # bucket, like the update_rows row buckets)
    _NONZERO_BUCKETS = (1024, 4096, 16384, 65536, 262144)

    def fetch(self, handle: dict) -> np.ndarray:
        """Force a schedule_async handle to a host int32 [C, N] array."""
        if "sparse" in handle:
            s = handle["sparse"]
            ci = np.asarray(s["ci"]).astype(np.int64)
            ni = np.asarray(s["ni"]).astype(np.int64)
            vals = np.asarray(s["vals"]).astype(np.int32)
            dense = np.zeros(handle["shape"], np.int32)
            # plain assignment, not add: every duplicate index pair is a
            # padding replica of cell (0,0) carrying the same value
            dense[ci, ni] = vals
            return dense
        return np.asarray(handle["out"]).astype(np.int32)

    def schedule(self, demands: np.ndarray, counts: np.ndarray,
                 spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
                 algo: str = "scan") -> np.ndarray:
        pad = bucket_size(demands.shape[0])
        d, k = pad_problem(np.asarray(demands, np.float32), np.asarray(counts), pad)
        if algo in ("rounds", "chunked"):
            # padded classes demand INF_FIT of resource 0, so they are inert
            # in the matrix passes, but resource 0 must stay in the active
            # set for that guard to execute
            active = tuple(int(i) for i in np.flatnonzero((d > 0).any(axis=0)))
            if algo == "chunked":
                assigned, new_avail = schedule_classes_chunked(
                    self.avail, self.total, self.alive,
                    jnp.asarray(d), jnp.asarray(k), spread_threshold,
                    active_idx=active,
                )
            else:
                assigned, new_avail = schedule_classes_rounds(
                    self.avail, self.total, self.alive,
                    jnp.asarray(d), jnp.asarray(k), spread_threshold,
                    active_idx=active,
                )
        else:
            assigned, new_avail = schedule_classes(
                self.avail, self.total, self.alive,
                jnp.asarray(d), jnp.asarray(k), spread_threshold,
            )
        self.avail = new_avail
        out = assigned[: demands.shape[0]]
        if out.shape[0] == 0:
            return np.asarray(out)
        # Narrow-dtype device->host transfer: the dense [C, N] int32 result
        # is the round's dominant host link cost (10.5MB at 256x10240; the
        # axon tunnel has been measured as low as ~35MB/s). A class can
        # place at most its own count on one node, so max(counts) bounds
        # every cell HOST-side; when that already proves uint8 the scalar
        # device-max sync (a full round trip) is skipped entirely.
        # Otherwise the exact device max is worth one sync: typical spreads
        # put 0-1 task per cell, and uint8-vs-int16 is 2.6MB vs 5.2MB per
        # round on the wire.
        m = int(np.max(counts, initial=0))
        if m >= 256:
            m = int(out.max())
        if m < 256:
            return np.asarray(out.astype(jnp.uint8)).astype(np.int32)
        if m < 32768:
            return np.asarray(out.astype(jnp.int16)).astype(np.int32)
        return np.asarray(out)
