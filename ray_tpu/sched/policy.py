"""Pluggable scheduling policies over the batched kernels.

Reference: src/ray/raylet/scheduling/policy/scheduling_policy.h defines
ISchedulingPolicy::Schedule dispatched by composite_scheduling_policy.cc; the
per-request policy set is hybrid/spread/random/node-affinity/node-label.
Here a policy consumes the whole pending queue (grouped into scheduling
classes) per round instead of one request, and selects the compute backend:
``numpy`` (CPU fallback) or ``jax`` (TPU) — the `policy="jax_tpu"` hook from
BASELINE.json's north star.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Optional, Tuple

import numpy as np

from ray_tpu.sched import kernel_np
from ray_tpu.sched.resources import NodeResourceState

logger = logging.getLogger(__name__)


def _invariant_violation(avail, demands, counts, assigned):
    """Check a round's assignment against the two safety invariants.

    Returns (error, taken): error is None when the assignment is safe,
    else a short description of the violated invariant; taken is the
    [N, R] usage matrix (computed here anyway, reused by the caller to
    update availability — the matmul is the expensive part at 10k nodes).
    `avail` is the PRE-round availability [N, R]. A small relative
    tolerance absorbs legitimate float32 subtraction noise; real kernel
    faults (over-assignment) exceed it by whole demand units.
    """
    if (assigned < 0).any():
        return "negative assignment count", None
    per_class = assigned.sum(axis=1)
    if (per_class > np.asarray(counts)).any():
        c = int(np.argmax(per_class - np.asarray(counts)))
        return (f"assigned > demand for class {c} "
                f"({int(per_class[c])} > {int(counts[c])})"), None
    taken = assigned.astype(np.float32).T @ demands  # [N, R]
    # tolerance scaled to float32 rounding (~32 ulp), NOT a fixed relative
    # fraction: large-magnitude resources (memory in bytes, ~2**33) would
    # otherwise get a tolerance bigger than a whole task's demand and real
    # over-commits would pass silently
    tol = 32.0 * np.finfo(np.float32).eps * np.maximum(avail, 1.0)
    over = taken > avail + tol
    if over.any():
        n, r = np.unravel_index(int(np.argmax(over)), over.shape)
        return (f"usage > availability at node {n} resource {r} "
                f"({taken[n, r]:.6g} > {avail[n, r]:.6g})"), taken
    return None, taken


class SchedulingPolicy:
    """Schedule per-class pending counts onto nodes.

    schedule() returns assigned[C, N] int32; under-assignment means the
    remainder is currently infeasible and stays queued (reference:
    cluster_task_manager.cc infeasible/waiting queues).
    """

    name = "base"

    def schedule(
        self, state: NodeResourceState, demands: np.ndarray, counts: np.ndarray
    ) -> np.ndarray:
        raise NotImplementedError

    def close(self) -> None:
        pass


class HybridPolicy(SchedulingPolicy):
    """Default policy: pack-until-threshold then spread (reference:
    hybrid_scheduling_policy.cc). backend="jax" keeps the cluster view
    device-resident via kernel_jax.JaxScheduler.

    Incremental device sync: between rounds the control plane mutates node
    availability through NodeResourceState.allocate/release, which records
    dirty row indices. The jax backend uploads ONLY those rows
    (JaxScheduler.update_rows) instead of the full [N, R] view; a full
    re-upload happens only on topology change or every
    FULL_SYNC_INTERVAL rounds (drift guard for non-dyadic fractional
    demands, whose subtraction order can differ host vs device by 1 ulp).
    """

    FULL_SYNC_INTERVAL = 64

    def __init__(self, spread_threshold: float = 0.5, backend: str = "numpy",
                 algo: str = "scan", device_min_cells: int = 262_144,
                 pipeline_depth: int = 8):
        self.spread_threshold = spread_threshold
        self.backend = backend
        self.algo = algo
        # jax backend only: problems below this many [classes x nodes]
        # cells run on the bit-identical NumPy twin instead — a device
        # dispatch (worse: a tunneled one) costs more than the whole
        # solve at small sizes, and the live GCS schedules MANY small
        # rounds between big ones. 0 forces every round onto the device.
        self.device_min_cells = device_min_cells
        # pipelined device rounds (see schedule_pipelined): how many
        # submitted rounds may be in flight before the oldest is forced
        self.pipeline_depth = pipeline_depth
        self._pipe: deque = deque()  # (tags, demands, submitted_counts, handle)
        self._pipe_inflight: dict = {}  # tag-key -> submitted-but-unfetched
        # fetched-but-undispatched results (window flushes buffer here; the
        # caller drains one per round)
        self._ready: deque = deque()
        self._pipe_topology = None  # topology the in-flight window solved
        self._jax = None  # lazily built JaxScheduler (topology-dependent)
        self._topology_key = None
        self._rounds_since_full_sync = 0
        # per-demand feasible-node counts (total capacity), cached per
        # topology: feeds the constrained-first class ordering
        self._feas_cache: dict = {}
        self._feas_cache_key = None

    def _constrained_order(self, state, demands: np.ndarray) -> np.ndarray:
        """Most-constrained classes first (kernel_np.constrained_order
        semantics), with the per-class feasible count memoized by demand
        bytes — totals only change on topology events, and rebuilding the
        [C, N, R] comparison every round at 10k nodes would cost ~10ms."""
        key = self._topology_of(state)
        if self._feas_cache_key != key:
            self._feas_cache = {}
            self._feas_cache_key = key
        feas = np.empty(len(demands), np.int64)
        for i, d in enumerate(demands):
            k = d.tobytes()
            v = self._feas_cache.get(k)
            if v is None:
                v = kernel_np.feasible_node_count(
                    state.total, state.alive, d
                )
                self._feas_cache[k] = v
            feas[i] = v
        return np.argsort(feas, kind="stable")

    @property
    def name(self):
        return "hybrid" if self.backend == "numpy" else "jax_tpu"

    def _jax_sched(self, state: NodeResourceState):
        from ray_tpu.sched.kernel_jax import JaxScheduler

        key = self._topology_of(state)
        if self._jax is None or self._topology_key != key:
            self._jax = JaxScheduler(state.total, state.alive)
            self._topology_key = key
            state.consume_dirty()  # fresh build IS the sync
            self._jax.set_available(state.available)
            self._rounds_since_full_sync = 0
            return self._jax
        dirty = state.consume_dirty()
        n = len(state.node_ids)
        if (
            self._rounds_since_full_sync >= self.FULL_SYNC_INTERVAL
            or len(dirty) * 2 >= n
        ):
            self._jax.set_available(state.available)
            self._rounds_since_full_sync = 0
        elif dirty:
            self._jax.update_rows(dirty, state.available[dirty])
        return self._jax

    # ------------------------------------------------ pipelined device path

    @property
    def pipelined(self) -> bool:
        """True when the live control plane should drive this policy via
        schedule_pipelined (jax backend with a pipeline window)."""
        return self.backend == "jax" and self.pipeline_depth > 0

    def has_inflight(self) -> bool:
        return bool(self._pipe) or bool(self._ready)

    def _topology_of(self, state) -> tuple:
        # O(1): the version counter bumps on add/remove/revive — the only
        # mutators of total/alive (tobytes() here cost ~2MB of memcpy per
        # round at 10k nodes)
        return (len(state.node_ids), state.topology_version)

    def _fetch_one(self, state):
        """Pop + force the oldest in-flight round; guard, debit the host,
        release the in-flight counts. Returns a dispatch plan, or None if
        the guard tripped (whole window discarded, device re-sync forced)."""
        tags_r, demands_r, eff_r, handle = self._pipe.popleft()
        assigned = self._jax.fetch(handle)[handle["inv"]]
        for c, t in enumerate(tags_r):
            left = self._pipe_inflight.get(t, 0) - int(eff_r[c])
            if left > 0:
                self._pipe_inflight[t] = left
            else:
                self._pipe_inflight.pop(t, None)
        err, taken = _invariant_violation(
            state.available, demands_r, eff_r, assigned
        )
        if err is not None:
            logger.warning(
                "pipelined jax_tpu round violated scheduling invariant "
                "(%s); discarding the in-flight window and re-syncing "
                "the device", err
            )
            self._discard_window()
            return None
        state.available = np.maximum(state.available - taken, 0.0)
        return tags_r, demands_r, assigned

    def _discard_window(self, state=None):
        """Drop every in-flight round. With `state`, ALSO drop buffered
        ready plans, crediting their host debits back — used on topology
        changes, where a buffered plan may target a node that no longer
        exists (its tasks stayed queued and simply reschedule)."""
        self._pipe.clear()
        self._pipe_inflight.clear()
        if state is not None:
            while self._ready:
                _, demands_r, assigned = self._ready.popleft()
                taken = assigned.astype(np.float32).T @ demands_r
                state.available = np.minimum(
                    state.available + taken, state.total
                )
        self._pipe_topology = None
        self._rounds_since_full_sync = self.FULL_SYNC_INTERVAL

    def _flush_pipe(self, state):
        """Force every in-flight round into the ready buffer (results are
        dispatched one per subsequent call — never dropped). Runs before
        any host->device sync: syncing mid-window would overwrite the
        device's in-flight debits with host values that lack them."""
        while self._pipe:
            plan = self._fetch_one(state)
            if plan is not None:
                self._ready.append(plan)

    def schedule_pipelined(self, state, demands, counts, tags):
        """Deep-pipelined device rounds for the LIVE control plane.

        Instead of submit->sync->dispatch per round (one full link round
        trip each — ~67ms on a degraded tunnel), rounds are ENQUEUED
        against the device-resident availability (which the kernel
        already carries forward on-device) and the oldest in-flight
        round is forced only once the window fills. The caller receives
        (tags, demands, assignment) of a PREVIOUS round — tasks stay
        queued until their round's result lands, so placement simply
        lags by the window depth while per-round cost drops to
        ~latency/depth + compute.

        Flow control: per-tag in-flight counts are subtracted from the
        submitted queue depths so a task is never scheduled twice while
        its round is still in flight. Unplaced remainders re-enter
        automatically when their round is fetched.

        Safety: the fetched assignment passes the same invariant guard
        as the sync path, checked against the host availability at fetch
        time (releases since submit only ADD availability, so the check
        is conservative); on violation the whole pipeline is discarded
        and the device fully re-synced.

        tags: opaque per-class identifiers (the GCS passes its class
        keys) used for the in-flight accounting and handed back with the
        result so the caller can map rows to its queues.
        """
        if (
            len(tags)
            and not self._pipe
            and not self._ready
            and demands.shape[0] * len(state.node_ids)
            < self.device_min_cells
        ):
            # small round with nothing in flight: the bit-identical NumPy
            # twin wins below device_min_cells (a tunneled dispatch costs
            # more than the whole solve), exactly as on the sync path.
            # Mixing is safe only when the pipe is EMPTY — the twin reads
            # host availability, which in-flight device rounds haven't
            # debited yet.
            return tags, demands, self.schedule(state, demands, counts)
        # topology changed mid-window (node add/remove): in-flight rounds
        # AND buffered ready plans solved a different cluster shape —
        # discard both (ready plans could target a node that just died;
        # their host debits are credited back and the tasks reschedule)
        if (
            (self._pipe or self._ready)
            and self._pipe_topology is not None
            and self._pipe_topology != self._topology_of(state)
        ):
            logger.info(
                "pipelined jax_tpu: topology changed mid-window; "
                "discarding %d in-flight + %d buffered rounds",
                len(self._pipe), len(self._ready),
            )
            self._discard_window(state)
        submitted = False
        if len(tags):
            state.enable_delta_log()  # mid-window syncs ride as increments
            eff = np.asarray(counts).copy()
            for c, t in enumerate(tags):
                eff[c] = max(0, eff[c] - self._pipe_inflight.get(t, 0))
            if eff.sum() > 0:
                # An ABSOLUTE host->device sync (dirty rows / periodic
                # full upload) would overwrite in-flight debits that
                # exist only on the device. Mid-window, availability
                # changes (completions releasing, out-of-band allocates)
                # ship as accumulated DELTAS instead — correct on top of
                # the device's in-flight state. Only the periodic
                # float-drift guard still forces a flush-then-full-sync.
                needs_full = (
                    self._rounds_since_full_sync >= self.FULL_SYNC_INTERVAL
                    or self._jax is None
                    or self._topology_key != self._topology_of(state)
                )
                if self._pipe and needs_full:
                    self._flush_pipe(state)
                if self._pipe:
                    sched = self._jax
                    delta = state.consume_delta()
                    if delta is not None:
                        state.consume_dirty()  # subsumed by the delta
                        sched.apply_delta(delta)
                else:
                    state.consume_delta()  # absolute sync supersedes it
                    sched = self._jax_sched(state)
                self._rounds_since_full_sync += 1
                order = self._constrained_order(state, demands)
                inv = np.empty_like(order)
                inv[order] = np.arange(len(order))
                handle = sched.schedule_async(
                    demands[order], eff[order], self.spread_threshold,
                    algo=self.algo,
                )
                handle["inv"] = inv
                self._pipe.append((list(tags), demands, eff, handle))
                self._pipe_topology = self._topology_of(state)
                for c, t in enumerate(tags):
                    self._pipe_inflight[t] = (
                        self._pipe_inflight.get(t, 0) + int(eff[c])
                    )
                submitted = True
        # dispatch: buffered results first, then the window's oldest once
        # it overfills (or whenever nothing new was enqueued — the drain
        # and flush tails must always make progress)
        if self._ready:
            return self._ready.popleft()
        if not self._pipe:
            return None
        if submitted and len(self._pipe) <= self.pipeline_depth:
            return None  # window still filling; nothing to dispatch yet
        return self._fetch_one(state)

    def schedule(self, state, demands, counts):
        # most-constrained classes first (measured: turns the masked-
        # feasibility makespan gap vs per-task greedy from +5% into ~-10%,
        # i.e. better than greedy — bench config 3)
        order = self._constrained_order(state, demands)
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        demands_o = demands[order]
        counts_o = np.asarray(counts)[order]
        use_device = (
            self.backend == "jax"
            and demands.shape[0] * len(state.node_ids) >= self.device_min_cells
        )
        if use_device:
            sched = self._jax_sched(state)
            self._rounds_since_full_sync += 1
            assigned = sched.schedule(
                demands_o, counts_o, self.spread_threshold, algo=self.algo
            )[inv]
            # Live-path numerics guard: the TPU kernel's fast division can
            # shift decisions ±1 at exact-capacity boundaries
            # (kernel_jax.py header note). The two safety invariants —
            # assigned ≤ demand per class, usage ≤ availability per node —
            # must hold on EVERY live round, not just in bench.py. On
            # violation: log, discard the device result, force a full
            # device re-sync, and serve this round from the NumPy twin.
            err, taken = _invariant_violation(
                state.available, demands, counts, assigned
            )
            if err is None:
                # keep the host view authoritative (device copy is a
                # cache); this assignment bypasses dirty tracking on
                # purpose — the device already holds the post-schedule
                # view (kernel output)
                state.available = np.maximum(state.available - taken, 0.0)
                return assigned
            logger.warning(
                "jax_tpu device round violated scheduling invariant (%s); "
                "falling back to the NumPy twin for this round", err
            )
            # fall through: the backend=="jax" branch below forces the full
            # device re-sync, and the NumPy path serves this round
        if self.backend == "jax":
            # small round on the NumPy twin: the device availability cache
            # goes stale, so force a full re-upload before the next
            # device-sized round
            self._rounds_since_full_sync = self.FULL_SYNC_INTERVAL
        if self.algo == "rounds":
            assigned, new_avail = kernel_np.schedule_classes_rounds(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        elif self.algo == "chunked":
            assigned, new_avail = kernel_np.schedule_classes_chunked(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        else:
            assigned, new_avail = kernel_np.schedule_classes(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        state.replace_available(new_avail)
        return assigned[inv]


class SpreadPolicy(SchedulingPolicy):
    """Round-robin over feasible nodes (reference: spread_scheduling_policy.cc)."""

    name = "spread"

    def __init__(self):
        self._cursor = 0

    def schedule(self, state, demands, counts):
        C = demands.shape[0]
        N = len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        for c in range(C):
            expand = np.repeat(demands[c][None, :], int(counts[c]), axis=0)
            nodes, new_avail = kernel_np.spread_assign(
                state.available, state.total, state.alive, expand, start=self._cursor
            )
            state.replace_available(new_avail)
            placed = nodes[nodes >= 0]
            if len(placed):
                np.add.at(assigned[c], placed, 1)
                self._cursor = (int(placed[-1]) + 1) % max(N, 1)
        return assigned


class RandomPolicy(SchedulingPolicy):
    """Uniform-random placement over feasible nodes (reference:
    random_scheduling_policy.cc). Seeded for reproducibility — the kernels
    stay deterministic; randomness lives only in this policy."""

    name = "random"

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def schedule(self, state, demands, counts):
        C = demands.shape[0]
        N = len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        avail = state.available
        for c in range(C):
            d = demands[c]
            for _ in range(int(counts[c])):
                feas = kernel_np.feasible_mask(avail, state.alive, d)
                if not feas.any():
                    break
                n = int(self._rng.choice(np.flatnonzero(feas)))
                avail[n] = np.maximum(avail[n] - d, 0.0)
                state.dirty_rows.add(n)
                assigned[c, n] += 1
        return assigned


class NodeAffinityPolicy(SchedulingPolicy):
    """Pin to a specific node, optionally soft (reference:
    node_affinity_scheduling_policy.cc)."""

    name = "node_affinity"

    def __init__(self, node_id: str, soft: bool = False, fallback: Optional[SchedulingPolicy] = None):
        self.node_id = node_id
        self.soft = soft
        self.fallback = fallback or HybridPolicy()

    def schedule(self, state, demands, counts):
        idx = state.node_index(self.node_id)
        C, N = demands.shape[0], len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        leftover = counts.copy()
        if idx is not None and state.alive[idx]:
            for c in range(C):
                fit = kernel_np._class_fit(
                    state.available, state.alive, demands[c]
                )[idx]
                take = int(min(fit, leftover[c]))
                if take > 0:
                    assigned[c, idx] = take
                    state.available[idx] = np.maximum(
                        state.available[idx] - take * demands[c], 0.0
                    )
                    leftover[c] -= take
        if self.soft and leftover.any():
            assigned += self.fallback.schedule(state, demands, leftover)
        return assigned


_POLICIES = {
    "hybrid": lambda **kw: HybridPolicy(backend="numpy", **kw),
    "jax_tpu": lambda **kw: HybridPolicy(backend="jax", **kw),
    "spread": lambda **kw: SpreadPolicy(),
    "random": lambda **kw: RandomPolicy(**kw),
}


def make_policy_from_config(config) -> SchedulingPolicy:
    """Build the cluster scheduling policy from a Config (the composite
    dispatch point — reference: composite_scheduling_policy.cc reading
    RAY_CONFIG knobs)."""
    kw = {}
    name = config.scheduling_policy
    if name in ("hybrid", "jax_tpu"):
        kw["spread_threshold"] = config.scheduler_spread_threshold
        kw["algo"] = config.scheduler_kernel_algo
        kw["device_min_cells"] = config.jax_policy_min_cells
        kw["pipeline_depth"] = config.jax_policy_pipeline_depth
    return make_policy(name, **kw)


def make_policy(name: str, **kwargs) -> SchedulingPolicy:
    try:
        return _POLICIES[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown scheduling policy {name!r}; have {list(_POLICIES)}")
