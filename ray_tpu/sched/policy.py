"""Pluggable scheduling policies over the batched kernels.

Reference: src/ray/raylet/scheduling/policy/scheduling_policy.h defines
ISchedulingPolicy::Schedule dispatched by composite_scheduling_policy.cc; the
per-request policy set is hybrid/spread/random/node-affinity/node-label.
Here a policy consumes the whole pending queue (grouped into scheduling
classes) per round instead of one request, and selects the compute backend:
``numpy`` (CPU fallback) or ``jax`` (TPU) — the `policy="jax_tpu"` hook from
BASELINE.json's north star.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np

from ray_tpu.sched import kernel_np
from ray_tpu.sched.resources import NodeResourceState

logger = logging.getLogger(__name__)


def _invariant_violation(avail, demands, counts, assigned):
    """Check a round's assignment against the two safety invariants.

    Returns (error, taken): error is None when the assignment is safe,
    else a short description of the violated invariant; taken is the
    [N, R] usage matrix (computed here anyway, reused by the caller to
    update availability — the matmul is the expensive part at 10k nodes).
    `avail` is the PRE-round availability [N, R]. A small relative
    tolerance absorbs legitimate float32 subtraction noise; real kernel
    faults (over-assignment) exceed it by whole demand units.
    """
    if (assigned < 0).any():
        return "negative assignment count", None
    per_class = assigned.sum(axis=1)
    if (per_class > np.asarray(counts)).any():
        c = int(np.argmax(per_class - np.asarray(counts)))
        return (f"assigned > demand for class {c} "
                f"({int(per_class[c])} > {int(counts[c])})"), None
    taken = assigned.astype(np.float32).T @ demands  # [N, R]
    # tolerance scaled to float32 rounding (~32 ulp), NOT a fixed relative
    # fraction: large-magnitude resources (memory in bytes, ~2**33) would
    # otherwise get a tolerance bigger than a whole task's demand and real
    # over-commits would pass silently
    tol = 32.0 * np.finfo(np.float32).eps * np.maximum(avail, 1.0)
    over = taken > avail + tol
    if over.any():
        n, r = np.unravel_index(int(np.argmax(over)), over.shape)
        return (f"usage > availability at node {n} resource {r} "
                f"({taken[n, r]:.6g} > {avail[n, r]:.6g})"), taken
    return None, taken


class SchedulingPolicy:
    """Schedule per-class pending counts onto nodes.

    schedule() returns assigned[C, N] int32; under-assignment means the
    remainder is currently infeasible and stays queued (reference:
    cluster_task_manager.cc infeasible/waiting queues).
    """

    name = "base"

    def schedule(
        self, state: NodeResourceState, demands: np.ndarray, counts: np.ndarray
    ) -> np.ndarray:
        raise NotImplementedError

    def close(self) -> None:
        pass


class HybridPolicy(SchedulingPolicy):
    """Default policy: pack-until-threshold then spread (reference:
    hybrid_scheduling_policy.cc). backend="jax" keeps the cluster view
    device-resident via kernel_jax.JaxScheduler.

    Incremental device sync: between rounds the control plane mutates node
    availability through NodeResourceState.allocate/release, which records
    dirty row indices. The jax backend uploads ONLY those rows
    (JaxScheduler.update_rows) instead of the full [N, R] view; a full
    re-upload happens only on topology change or every
    FULL_SYNC_INTERVAL rounds (drift guard for non-dyadic fractional
    demands, whose subtraction order can differ host vs device by 1 ulp).
    """

    FULL_SYNC_INTERVAL = 64

    def __init__(self, spread_threshold: float = 0.5, backend: str = "numpy",
                 algo: str = "scan", device_min_cells: int = 262_144):
        self.spread_threshold = spread_threshold
        self.backend = backend
        self.algo = algo
        # jax backend only: problems below this many [classes x nodes]
        # cells run on the bit-identical NumPy twin instead — a device
        # dispatch (worse: a tunneled one) costs more than the whole
        # solve at small sizes, and the live GCS schedules MANY small
        # rounds between big ones. 0 forces every round onto the device.
        self.device_min_cells = device_min_cells
        self._jax = None  # lazily built JaxScheduler (topology-dependent)
        self._topology_key = None
        self._rounds_since_full_sync = 0
        # per-demand feasible-node counts (total capacity), cached per
        # topology: feeds the constrained-first class ordering
        self._feas_cache: dict = {}
        self._feas_cache_key = None

    def _constrained_order(self, state, demands: np.ndarray) -> np.ndarray:
        """Most-constrained classes first (kernel_np.constrained_order
        semantics), with the per-class feasible count memoized by demand
        bytes — totals only change on topology events, and rebuilding the
        [C, N, R] comparison every round at 10k nodes would cost ~10ms."""
        key = (len(state.node_ids), state.total.tobytes(),
               state.alive.tobytes())
        if self._feas_cache_key != key:
            self._feas_cache = {}
            self._feas_cache_key = key
        feas = np.empty(len(demands), np.int64)
        for i, d in enumerate(demands):
            k = d.tobytes()
            v = self._feas_cache.get(k)
            if v is None:
                v = kernel_np.feasible_node_count(
                    state.total, state.alive, d
                )
                self._feas_cache[k] = v
            feas[i] = v
        return np.argsort(feas, kind="stable")

    @property
    def name(self):
        return "hybrid" if self.backend == "numpy" else "jax_tpu"

    def _jax_sched(self, state: NodeResourceState):
        from ray_tpu.sched.kernel_jax import JaxScheduler

        key = (len(state.node_ids), state.total.tobytes(), state.alive.tobytes())
        if self._jax is None or self._topology_key != key:
            self._jax = JaxScheduler(state.total, state.alive)
            self._topology_key = key
            state.consume_dirty()  # fresh build IS the sync
            self._jax.set_available(state.available)
            self._rounds_since_full_sync = 0
            return self._jax
        dirty = state.consume_dirty()
        n = len(state.node_ids)
        if (
            self._rounds_since_full_sync >= self.FULL_SYNC_INTERVAL
            or len(dirty) * 2 >= n
        ):
            self._jax.set_available(state.available)
            self._rounds_since_full_sync = 0
        elif dirty:
            self._jax.update_rows(dirty, state.available[dirty])
        return self._jax

    def schedule(self, state, demands, counts):
        # most-constrained classes first (measured: turns the masked-
        # feasibility makespan gap vs per-task greedy from +5% into ~-10%,
        # i.e. better than greedy — bench config 3)
        order = self._constrained_order(state, demands)
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        demands_o = demands[order]
        counts_o = np.asarray(counts)[order]
        use_device = (
            self.backend == "jax"
            and demands.shape[0] * len(state.node_ids) >= self.device_min_cells
        )
        if use_device:
            sched = self._jax_sched(state)
            self._rounds_since_full_sync += 1
            assigned = sched.schedule(
                demands_o, counts_o, self.spread_threshold, algo=self.algo
            )[inv]
            # Live-path numerics guard: the TPU kernel's fast division can
            # shift decisions ±1 at exact-capacity boundaries
            # (kernel_jax.py header note). The two safety invariants —
            # assigned ≤ demand per class, usage ≤ availability per node —
            # must hold on EVERY live round, not just in bench.py. On
            # violation: log, discard the device result, force a full
            # device re-sync, and serve this round from the NumPy twin.
            err, taken = _invariant_violation(
                state.available, demands, counts, assigned
            )
            if err is None:
                # keep the host view authoritative (device copy is a
                # cache); this assignment bypasses dirty tracking on
                # purpose — the device already holds the post-schedule
                # view (kernel output)
                state.available = np.maximum(state.available - taken, 0.0)
                return assigned
            logger.warning(
                "jax_tpu device round violated scheduling invariant (%s); "
                "falling back to the NumPy twin for this round", err
            )
            # fall through: the backend=="jax" branch below forces the full
            # device re-sync, and the NumPy path serves this round
        if self.backend == "jax":
            # small round on the NumPy twin: the device availability cache
            # goes stale, so force a full re-upload before the next
            # device-sized round
            self._rounds_since_full_sync = self.FULL_SYNC_INTERVAL
        if self.algo == "rounds":
            assigned, new_avail = kernel_np.schedule_classes_rounds(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        elif self.algo == "chunked":
            assigned, new_avail = kernel_np.schedule_classes_chunked(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        else:
            assigned, new_avail = kernel_np.schedule_classes(
                state.available, state.total, state.alive,
                demands_o, counts_o,
                spread_threshold=self.spread_threshold,
            )
        state.replace_available(new_avail)
        return assigned[inv]


class SpreadPolicy(SchedulingPolicy):
    """Round-robin over feasible nodes (reference: spread_scheduling_policy.cc)."""

    name = "spread"

    def __init__(self):
        self._cursor = 0

    def schedule(self, state, demands, counts):
        C = demands.shape[0]
        N = len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        for c in range(C):
            expand = np.repeat(demands[c][None, :], int(counts[c]), axis=0)
            nodes, new_avail = kernel_np.spread_assign(
                state.available, state.total, state.alive, expand, start=self._cursor
            )
            state.replace_available(new_avail)
            placed = nodes[nodes >= 0]
            if len(placed):
                np.add.at(assigned[c], placed, 1)
                self._cursor = (int(placed[-1]) + 1) % max(N, 1)
        return assigned


class RandomPolicy(SchedulingPolicy):
    """Uniform-random placement over feasible nodes (reference:
    random_scheduling_policy.cc). Seeded for reproducibility — the kernels
    stay deterministic; randomness lives only in this policy."""

    name = "random"

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def schedule(self, state, demands, counts):
        C = demands.shape[0]
        N = len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        avail = state.available
        for c in range(C):
            d = demands[c]
            for _ in range(int(counts[c])):
                feas = kernel_np.feasible_mask(avail, state.alive, d)
                if not feas.any():
                    break
                n = int(self._rng.choice(np.flatnonzero(feas)))
                avail[n] = np.maximum(avail[n] - d, 0.0)
                state.dirty_rows.add(n)
                assigned[c, n] += 1
        return assigned


class NodeAffinityPolicy(SchedulingPolicy):
    """Pin to a specific node, optionally soft (reference:
    node_affinity_scheduling_policy.cc)."""

    name = "node_affinity"

    def __init__(self, node_id: str, soft: bool = False, fallback: Optional[SchedulingPolicy] = None):
        self.node_id = node_id
        self.soft = soft
        self.fallback = fallback or HybridPolicy()

    def schedule(self, state, demands, counts):
        idx = state.node_index(self.node_id)
        C, N = demands.shape[0], len(state)
        assigned = np.zeros((C, N), dtype=np.int32)
        leftover = counts.copy()
        if idx is not None and state.alive[idx]:
            for c in range(C):
                fit = kernel_np._class_fit(
                    state.available, state.alive, demands[c]
                )[idx]
                take = int(min(fit, leftover[c]))
                if take > 0:
                    assigned[c, idx] = take
                    state.available[idx] = np.maximum(
                        state.available[idx] - take * demands[c], 0.0
                    )
                    leftover[c] -= take
        if self.soft and leftover.any():
            assigned += self.fallback.schedule(state, demands, leftover)
        return assigned


_POLICIES = {
    "hybrid": lambda **kw: HybridPolicy(backend="numpy", **kw),
    "jax_tpu": lambda **kw: HybridPolicy(backend="jax", **kw),
    "spread": lambda **kw: SpreadPolicy(),
    "random": lambda **kw: RandomPolicy(**kw),
}


def make_policy_from_config(config) -> SchedulingPolicy:
    """Build the cluster scheduling policy from a Config (the composite
    dispatch point — reference: composite_scheduling_policy.cc reading
    RAY_CONFIG knobs)."""
    kw = {}
    name = config.scheduling_policy
    if name in ("hybrid", "jax_tpu"):
        kw["spread_threshold"] = config.scheduler_spread_threshold
        kw["algo"] = config.scheduler_kernel_algo
        kw["device_min_cells"] = config.jax_policy_min_cells
    return make_policy(name, **kw)


def make_policy(name: str, **kwargs) -> SchedulingPolicy:
    try:
        return _POLICIES[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown scheduling policy {name!r}; have {list(_POLICIES)}")
