"""NumPy reference scheduler kernels (the CPU fallback path).

These define the authoritative scheduling semantics; `kernel_jax` implements
the *identical math* under jit and is golden-tested for decision equality
(mirroring how the reference tests schedulers as pure functions on synthetic
resource views — e.g. src/ray/raylet/scheduling/cluster_resource_scheduler_test.cc,
policy/hybrid_scheduling_policy_test.cc).

Semantics reproduced from the reference's default HybridSchedulingPolicy
(src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc):
- a node's score is its *critical resource utilization* (max over resources of
  used/total), flattened to 0 while under `spread_threshold` (default 0.5,
  RAY_CONFIG scheduler_spread_threshold in src/ray/common/ray_config_def.h);
- the best (lowest-score) feasible node wins; ties break toward the lowest
  row index, and row 0 is the local node — giving the reference's
  pack-local-until-threshold-then-spread behavior.

Deliberate divergence: the reference adds top-k random tiebreak
(scheduler_top_k_fraction) to avoid thundering herds of independent raylets;
our decisions are made in batched rounds by one kernel, so they are kept
deterministic — required for NumPy/JAX decision equality.

Two granularities:
- `greedy_assign`: per-task loop, bit-exact reference semantics, used for
  small queues and as the makespan comparator.
- `schedule_classes`: the batched kernel. Tasks are grouped by *scheduling
  class* (identical demand vector — the same equivalence the reference uses
  for lease reuse in src/ray/core_worker/transport/normal_task_submitter.cc),
  and the kernel assigns per-class counts to nodes in vectorized passes.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

EPS = 1e-4
INF_FIT = np.int32(2**30)
DEFAULT_SPREAD_THRESHOLD = 0.5
MAX_PASSES = 8


def critical_util(avail: np.ndarray, total: np.ndarray) -> np.ndarray:
    """Per-node critical resource utilization: max_r used/total (total>0 only)."""
    used = total - avail
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(total > 0, used / np.maximum(total, EPS), 0.0)
    return frac.max(axis=1).astype(np.float32)


def node_scores(
    avail: np.ndarray,
    total: np.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
) -> np.ndarray:
    util = critical_util(avail, total)
    return np.where(util >= spread_threshold, util, 0.0).astype(np.float32)


def feasible_mask(avail: np.ndarray, alive: np.ndarray, demand: np.ndarray) -> np.ndarray:
    return np.all(avail + EPS >= demand[None, :], axis=1) & alive


def greedy_assign(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-task hybrid-policy placement, one task at a time (reference loop).

    Returns (assignment[T] int32 node row or -1, new availability). Mirrors
    ClusterResourceScheduler::GetBestSchedulableNode called per task.
    """
    avail = avail.astype(np.float32).copy()
    total = np.asarray(total, dtype=np.float32)
    T = demands.shape[0]
    out = np.full(T, -1, dtype=np.int32)
    for t in range(T):
        d = demands[t]
        feas = feasible_mask(avail, alive, d)
        if not feas.any():
            continue
        score = node_scores(avail, total, spread_threshold)
        score = np.where(feas, score, np.float32(np.inf))
        n = int(np.argmin(score))  # ties -> lowest row (local-first)
        out[t] = n
        avail[n] = np.maximum(avail[n] - d, 0.0)
    return out, avail


def _class_fit(avail: np.ndarray, alive: np.ndarray, demand: np.ndarray) -> np.ndarray:
    """How many tasks of `demand` fit on each node right now. [N] int32."""
    pos = demand > 0
    if not pos.any():
        return np.where(alive, INF_FIT, 0).astype(np.int32)
    ratios = np.floor((avail[:, pos] + EPS) / demand[pos][None, :])
    fit = ratios.min(axis=1)
    fit = np.clip(fit, 0, float(INF_FIT))
    return np.where(alive, fit, 0).astype(np.int32)


def _threshold_cap(
    avail: np.ndarray,
    total: np.ndarray,
    demand: np.ndarray,
    spread_threshold: float,
) -> np.ndarray:
    """Tasks until a node's critical utilization reaches the spread threshold.

    k_n = min over r with d_r>0 of floor((thr*total_r - used_r)/d_r); the +1
    matches per-task greedy, which still places the task that *crosses* the
    threshold (scores are computed before placement).
    """
    pos = demand > 0
    if not pos.any():
        return np.full(avail.shape[0], INF_FIT, dtype=np.int32)
    used = total - avail
    head = spread_threshold * total[:, pos] - used[:, pos]
    k = np.floor((head + EPS) / demand[pos][None, :]).min(axis=1)
    k = np.clip(k, 0, float(INF_FIT) - 1)
    return (k + 1).astype(np.int32)


def _fill_by_score(
    take_cap: np.ndarray, score: np.ndarray, remaining: int
) -> np.ndarray:
    """Take up to `take_cap[n]` from nodes in ascending-score order (stable)
    until `remaining` is exhausted. Vectorized prefix fill. [N] int32."""
    order = np.argsort(score, kind="stable")
    cap_sorted = take_cap[order].astype(np.int64)
    cum = np.cumsum(cap_sorted)
    prev = cum - cap_sorted
    take_sorted = np.clip(remaining - prev, 0, cap_sorted)
    take = np.zeros_like(take_sorted)
    take[order] = take_sorted
    return take.astype(np.int32)


# Number of quantized score levels in the class kernel's fill. Sorting 10k
# float scores per class is the TPU bottleneck; quantizing utilization into
# buckets turns the sort into a one-hot cumsum (MXU/VPU work) at the cost of
# within-bucket ties breaking by node index — bounded score error 1/BUCKETS.
SCORE_BUCKETS = 64


def _score_bucket(
    util: np.ndarray, spread_threshold: float, n_buckets: int = SCORE_BUCKETS
) -> np.ndarray:
    """Quantize hybrid scores: bucket 0 = under threshold; 1..B-1 = utilization
    above threshold, linearly quantized. Stable sort by bucket == sort by
    (quantized score, node index) — the deterministic tiebreak."""
    over = (util - np.float32(spread_threshold)) / np.float32(
        max(1e-6, 1.0 - spread_threshold)
    )
    over = np.clip(over, 0.0, 1.0)
    b = np.where(
        util >= spread_threshold, 1.0 + np.floor(over * (n_buckets - 2)), 0.0
    )
    return np.clip(b, 0, n_buckets - 1).astype(np.int32)


def schedule_classes(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    counts: np.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    max_passes: int = MAX_PASSES,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched hybrid placement over scheduling classes.

    Args:
      avail, total: [N, R] float32 cluster view.
      alive: [N] bool.
      demands: [C, R] float32 per-class demand vectors.
      counts: [C] int32 pending task counts per class.
    Returns:
      (assigned[C, N] int32 counts, new availability [N, R]).
      sum(assigned[c]) < counts[c] means the remainder is currently infeasible
      (stays queued, like the reference's infeasible/waiting queues in
      cluster_task_manager.cc).

    Each class runs a few vectorized passes: fill under-threshold nodes up to
    the threshold in score order, then equal-share balance across feasible
    nodes — converging to the same shape per-task greedy produces.
    """
    avail = avail.astype(np.float32).copy()
    total = np.asarray(total, dtype=np.float32)
    C, _ = demands.shape
    N = avail.shape[0]
    assigned = np.zeros((C, N), dtype=np.int32)
    for c in range(C):
        d = demands[c]
        remaining = int(counts[c])
        for _ in range(max_passes):
            if remaining <= 0:
                break
            fit = _class_fit(avail, alive, d)
            n_feasible = int((fit > 0).sum())
            if n_feasible == 0:
                break
            util = critical_util(avail, total)
            bucket = _score_bucket(util, spread_threshold)
            under = util < spread_threshold
            cap_thresh = _threshold_cap(avail, total, d, spread_threshold)
            equal_share = np.int32(-(-remaining // n_feasible))  # ceil
            cap = np.where(under, cap_thresh, equal_share).astype(np.int32)
            cap = np.minimum(np.minimum(cap, fit), np.int32(remaining))
            take = _fill_by_score(cap, bucket.astype(np.float32), remaining)
            got = int(take.sum())
            if got == 0:
                break
            assigned[c] += take
            remaining -= got
            avail = np.maximum(avail - take[:, None].astype(np.float32) * d[None, :], 0.0)
    return assigned, avail


def _fit_matrix(avail, alive, demands):
    """[C, N] float32 fit counts; twin of kernel_jax._fit_matrix."""
    C, R = demands.shape
    N = avail.shape[0]
    fit = np.full((C, N), np.float32(INF_FIT), dtype=np.float32)
    for r in range(R):
        d_r = demands[:, r]
        ratio = np.floor(
            (avail[:, r][None, :] + np.float32(EPS))
            / np.maximum(d_r, np.float32(1e-9))[:, None]
        )
        fit = np.where(d_r[:, None] > 0, np.minimum(fit, ratio), fit)
    fit = np.clip(fit, 0.0, np.float32(INF_FIT))
    return fit * alive[None, :].astype(np.float32)


def _threshold_cap_matrix(avail, total, demands, thr):
    """[C, N] float32 tasks-until-threshold; twin of kernel_jax."""
    C, R = demands.shape
    N = avail.shape[0]
    used = total - avail
    k = np.full((C, N), np.float32(INF_FIT), dtype=np.float32)
    for r in range(R):
        d_r = demands[:, r]
        head = np.float32(thr) * total[:, r] - used[:, r]
        cap_r = np.floor(
            (head[None, :] + np.float32(EPS))
            / np.maximum(d_r, np.float32(1e-9))[:, None]
        )
        k = np.where(d_r[:, None] > 0, np.minimum(k, cap_r), k)
    return np.clip(k, 0.0, np.float32(INF_FIT) - 1.0) + np.float32(1.0)


# float32 holds ints exactly to 2**24; saturate prefix sums at 2**23.
SAT = float(1 << 23)


def _sat_cumsum(x: np.ndarray, axis: int) -> np.ndarray:
    """min(prefix_sum, SAT) — twin of kernel_jax._sat_cumsum (associative
    saturating scan == clipped exact cumsum for nonnegative inputs)."""
    return np.minimum(np.cumsum(x.astype(np.int64), axis=axis), np.int64(SAT)).astype(
        np.float32
    )


def schedule_classes_rounds(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    counts: np.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    rounds: int = 4,
) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy twin of kernel_jax.schedule_classes_rounds (the jax_tpu policy's
    CPU fallback): identical math, golden-tested for decision equality.
    See the jax docstring for the algorithm and exactness bounds."""
    thr = np.float32(spread_threshold)
    avail = avail.astype(np.float32).copy()
    total = np.asarray(total, np.float32)
    demands = demands.astype(np.float32)
    C, R = demands.shape
    N = avail.shape[0]
    alive_f = alive.astype(np.float32)
    remaining = counts.astype(np.float32)
    assigned = np.zeros((C, N), np.float32)

    def claim_phase(avail_p, remaining, cap):
        capc = np.minimum(cap, np.minimum(remaining[:, None], np.float32(SAT)))
        prev = _sat_cumsum(capc, axis=1) - capc
        want = np.clip(remaining[:, None] - prev, 0.0, capc)
        take = want.copy()
        for r in range(R):
            d_r = demands[:, r]
            usage_r = want * d_r[:, None]
            # fractional demands: cumsum in float32 to mirror jax exactly is
            # not possible here (int64 path requires integer quanta); match
            # the jax scan on the integer-granular case, which _sat_cumsum
            # guarantees only for integer-valued usage.
            prev_r = _sat_cumsum_f(usage_r, axis=0) - usage_r
            head = avail_p[None, :, r] - prev_r
            fit_r = np.floor(
                (head + np.float32(EPS)) / np.maximum(d_r, np.float32(1e-9))[:, None]
            )
            take = np.where(
                d_r[:, None] > 0,
                np.minimum(take, np.clip(fit_r, 0.0, np.float32(SAT))),
                take,
            )
        return np.clip(take, 0.0, want)

    def run_phase(avail, remaining, assigned, cap):
        # node-index fill order, matching the jax twin (see its run_phase
        # comment: exact for phase A, a measured quality tradeoff for B)
        take = claim_phase(avail, remaining, cap)
        usage = np.einsum("cn,cr->nr", take, demands).astype(np.float32)
        avail = np.maximum(avail - usage, 0.0)
        return avail, remaining - take.sum(axis=1), assigned + take

    for _ in range(rounds):
        util = critical_util(avail, total)
        under = (util < thr).astype(np.float32)[None, :] * alive_f[None, :]
        fit = _fit_matrix(avail, alive, demands)
        capA = np.minimum(fit, _threshold_cap_matrix(avail, total, demands, thr))
        avail, remaining, assigned = run_phase(
            avail, remaining, assigned, capA * under
        )
        fit = _fit_matrix(avail, alive, demands)
        n_feas = (fit > 0).sum(axis=1).astype(np.float32)
        share = np.ceil(remaining / np.maximum(n_feas, np.float32(1.0)))
        capB = np.minimum(fit, share[:, None])
        avail, remaining, assigned = run_phase(avail, remaining, assigned, capB)
    return assigned.astype(np.int32), avail


def schedule_classes_chunked(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    counts: np.ndarray,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
    chunk: int = 16,
    rounds: int = 2,
) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy twin of kernel_jax.schedule_classes_chunked: classes are placed
    `chunk` at a time by the two-phase rounds core, with availability carried
    between chunks (sequential at chunk granularity, parallel within). See
    the jax docstring for rationale; golden-tested decision equality on
    integer-granular problems. A trailing partial chunk is allowed here (the
    jax path pads instead)."""
    avail = avail.astype(np.float32).copy()
    C = demands.shape[0]
    out = []
    for s in range(0, C, chunk):
        a, avail = schedule_classes_rounds(
            avail, total, alive,
            demands[s : s + chunk], counts[s : s + chunk],
            spread_threshold, rounds,
        )
        out.append(a)
    if not out:
        return np.zeros((0, avail.shape[0]), np.int32), avail
    return np.concatenate(out, axis=0), avail


def _sat_cumsum_f(x: np.ndarray, axis: int) -> np.ndarray:
    """Saturating cumsum over possibly-fractional nonnegative float32 values.
    Sequential semantics = min(prefix, SAT); exact (and equal to the jax
    associative scan) when inputs are integer-valued with partials < 2**24."""
    cum = np.minimum(np.cumsum(x.astype(np.float64), axis=axis), SAT)
    return cum.astype(np.float32)


def feasible_node_counts(
    total: np.ndarray, alive: np.ndarray, demands: np.ndarray
) -> np.ndarray:
    """[C] how many nodes could EVER host each demand (total capacity, not
    current availability — stable across rounds). One [C, N, R] broadcast;
    shared by the simulator and the live policy so their class orderings
    can never diverge."""
    return (
        np.all(total[None, :, :] + EPS >= demands[:, None, :], axis=2)
        & alive[None, :]
    ).sum(axis=1)


def feasible_node_count(
    total: np.ndarray, alive: np.ndarray, demand: np.ndarray
) -> int:
    """Single-demand case of feasible_node_counts (policy cache misses)."""
    return int(feasible_node_counts(total, alive, demand[None, :])[0])


def constrained_order(
    total: np.ndarray, alive: np.ndarray, demands: np.ndarray
) -> np.ndarray:
    """Schedule most-constrained classes FIRST: order by feasible-node
    count. Unconstrained workloads are untouched (stable sort keeps equal
    counts in submission order); constrained ones stop losing their
    only-feasible nodes to flexible classes that could run anywhere.
    Measured effect: masked-feasibility makespan gap vs per-task greedy
    drops from ~5% to about -10% (bench config 3)."""
    return np.argsort(
        feasible_node_counts(total, alive, demands), kind="stable"
    )


def spread_assign(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    start: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """SPREAD strategy: round-robin over feasible nodes (reference:
    src/ray/raylet/scheduling/policy/spread_scheduling_policy.cc)."""
    avail = avail.astype(np.float32).copy()
    T = demands.shape[0]
    N = avail.shape[0]
    out = np.full(T, -1, dtype=np.int32)
    cursor = start % max(N, 1)
    for t in range(T):
        d = demands[t]
        feas = feasible_mask(avail, alive, d)
        if not feas.any():
            continue
        # first feasible node at/after the cursor, wrapping
        idx = np.flatnonzero(feas)
        pos = np.searchsorted(idx, cursor)
        n = int(idx[pos % len(idx)])
        out[t] = n
        avail[n] = np.maximum(avail[n] - d, 0.0)
        cursor = (n + 1) % N
    return out, avail


def expand_class_assignment(
    assigned: np.ndarray, class_task_ids: list
) -> list:
    """Expand [C, N] counts into per-task (task_id, node_row) pairs.

    `class_task_ids[c]` is the ordered list of task ids in class c; tasks are
    handed out to nodes in node-row order. Host-side (not jitted).
    """
    pairs = []
    for c, ids in enumerate(class_task_ids):
        k = 0
        row = assigned[c]
        for n in np.flatnonzero(row):
            cnt = int(row[n])
            for tid in ids[k : k + cnt]:
                pairs.append((tid, int(n)))
            k += cnt
    return pairs
