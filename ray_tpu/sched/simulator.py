"""Discrete-event makespan simulator for scheduler-quality measurement.

The north star (BASELINE.json) requires the batched TPU kernel to match the
reference's default per-task policy makespan within 3%. The reference has no
in-tree simulator; its scheduling quality is observed through release tests
(release/benchmarks/distributed/test_scheduling.py). Here quality is measured
directly: run the SAME synthetic timed workload to completion under

- ``greedy``  — per-task hybrid placement (`kernel_np.greedy_assign`
  semantics, the comparator: one task at a time, full rescore between tasks,
  mirroring ClusterResourceScheduler::GetBestSchedulableNode), and
- ``classes`` / ``rounds`` — the batched kernels (`schedule_classes`,
  `schedule_classes_rounds`) that place whole class-grouped queues per round,

and report makespan (the tick the last task finishes) for each. Time is
integer ticks; all tasks arrive at t=0 (offline makespan — the regime the
1M-task north-star round targets). Scheduling happens at t=0 and whenever
completions free resources, matching the event-driven reference loop
(ScheduleAndDispatchTasks runs on every state change).

Tasks are FIFO within a class and classes are visited in index order by both
schedulers, so the only difference measured is placement quality, not order.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.sched import kernel_np


@dataclass
class SimResult:
    makespan: int
    rounds: int
    decisions: int
    sched_time_s: float  # host time spent inside scheduler calls
    unplaced: int  # tasks that could never be placed (infeasible forever)


def _greedy_round(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    queue: List[int],
    spread_threshold: float,
) -> List[Tuple[int, int]]:
    """Place queued tasks one at a time (reference semantics). Mutates
    `avail` and `queue`. Returns [(class, node_row)] placements in order.

    A class whose demand fits nowhere is skipped for the whole round (exact:
    feasibility is class-wide, so no later task of that class could place
    either)."""
    placements: List[Tuple[int, int]] = []
    C = demands.shape[0]
    for c in range(C):
        while queue[c] > 0:
            d = demands[c]
            feas = kernel_np.feasible_mask(avail, alive, d)
            if not feas.any():
                break
            score = kernel_np.node_scores(avail, total, spread_threshold)
            score = np.where(feas, score, np.float32(np.inf))
            n = int(np.argmin(score))
            avail[n] = np.maximum(avail[n] - d, 0.0)
            queue[c] -= 1
            placements.append((c, n))
    return placements


def _batched_round(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    queue: List[int],
    spread_threshold: float,
    algo: str,
    jax_sched=None,
) -> Tuple[List[Tuple[int, int]], np.ndarray]:
    """One batched kernel round over the whole queue (most-constrained
    classes first, like the production policy). Returns (placements,
    new_avail); mutates `queue`."""
    order = kernel_np.constrained_order(total, alive, demands)
    inv = np.empty_like(order)
    inv[order] = np.arange(len(order))
    demands = demands[order]
    counts = np.array(queue, dtype=np.int32)[order]
    if jax_sched is not None:
        # the host view is authoritative (completions freed resources since
        # the last round); push it to the device before scheduling
        jax_sched.set_available(avail)
        assigned = jax_sched.schedule(
            demands, counts, spread_threshold, algo=algo
        )
        taken = assigned.astype(np.float32).T @ demands
        new_avail = np.maximum(avail - taken, 0.0)
    elif algo == "rounds":
        assigned, new_avail = kernel_np.schedule_classes_rounds(
            avail, total, alive, demands, counts,
            spread_threshold=spread_threshold,
        )
    elif algo == "chunked":
        assigned, new_avail = kernel_np.schedule_classes_chunked(
            avail, total, alive, demands, counts,
            spread_threshold=spread_threshold,
        )
    else:
        assigned, new_avail = kernel_np.schedule_classes(
            avail, total, alive, demands, counts,
            spread_threshold=spread_threshold,
        )
    assigned = np.asarray(assigned)[inv]  # back to caller's class indexing
    demands = demands[inv]
    placements: List[Tuple[int, int]] = []
    for c in range(demands.shape[0]):
        row = assigned[c]
        placed = int(row.sum())
        if placed <= 0:
            continue
        queue[c] -= placed
        for n in np.flatnonzero(row):
            placements.extend([(c, int(n))] * int(row[n]))
    return placements, new_avail


def simulate_makespan(
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    counts: np.ndarray,
    durations: Sequence[np.ndarray],
    scheduler: str = "greedy",
    spread_threshold: float = 0.5,
    jax_sched=None,
    max_rounds: int = 1_000_000,
) -> SimResult:
    """Run a workload to completion; return the makespan in ticks.

    Args:
      total: [N, R] cluster capacity; alive: [N] bool.
      demands: [C, R] per-class demand vectors.
      counts: [C] task counts (all arrive at t=0).
      durations: per-class int arrays, durations[c][i] = ticks for the i-th
        task of class c (consumed FIFO — both schedulers hand tasks out in
        class order, so task i of class c gets the same duration under both).
      scheduler: "greedy" | "classes" | "rounds" | "chunked".
      jax_sched: optional kernel_jax.JaxScheduler to run the batched kernels
        on device (its avail view must start equal to `total*alive`).
    """
    import time as _time

    avail = total.astype(np.float32).copy()
    avail *= alive[:, None].astype(np.float32)
    total = np.asarray(total, np.float32)
    C = demands.shape[0]
    queue = [int(c) for c in counts]
    next_task = [0] * C  # FIFO duration cursor per class
    events: List[Tuple[int, int, int]] = []  # (t_end, class, node)
    now = 0
    n_rounds = 0
    decisions = 0
    sched_time = 0.0
    total_tasks = int(sum(queue))

    def run_sched() -> int:
        nonlocal decisions, sched_time
        t0 = _time.perf_counter()
        if scheduler == "greedy":
            placements = _greedy_round(
                avail, total, alive, demands, queue, spread_threshold
            )
        else:
            placements, new_avail = _batched_round(
                avail, total, alive, demands, queue, spread_threshold,
                algo=scheduler, jax_sched=jax_sched,
            )
            avail[:] = new_avail
        sched_time += _time.perf_counter() - t0
        for c, n in placements:
            i = next_task[c]
            next_task[c] = i + 1
            dur = int(durations[c][i])
            heapq.heappush(events, (now + max(dur, 1), c, n))
        decisions += len(placements)
        return len(placements)

    run_sched()
    n_rounds += 1
    makespan = 0
    while events and n_rounds < max_rounds:
        now = events[0][0]
        # free everything completing at this tick, then one scheduling pass
        while events and events[0][0] == now:
            _, c, n = heapq.heappop(events)
            avail[n] = np.minimum(avail[n] + demands[c], total[n])
        makespan = now
        if any(q > 0 for q in queue):
            run_sched()
            n_rounds += 1
    unplaced = int(sum(queue))
    return SimResult(
        makespan=makespan,
        rounds=n_rounds,
        decisions=decisions,
        sched_time_s=sched_time,
        unplaced=unplaced,
    )


def makespan_gap_pct(
    total: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    counts: np.ndarray,
    durations: Sequence[np.ndarray],
    scheduler: str = "classes",
    spread_threshold: float = 0.5,
    jax_sched=None,
) -> Dict[str, float]:
    """Run greedy (reference comparator) and the batched scheduler on the
    identical workload; gap > 0 means the batched schedule is worse."""
    g = simulate_makespan(
        total, alive, demands, counts, durations, "greedy",
        spread_threshold,
    )
    b = simulate_makespan(
        total, alive, demands, counts, durations, scheduler,
        spread_threshold, jax_sched=jax_sched,
    )
    gap = (
        100.0 * (b.makespan - g.makespan) / g.makespan
        if g.makespan > 0 else 0.0
    )
    return {
        "makespan_greedy": g.makespan,
        "makespan_batched": b.makespan,
        "makespan_gap_pct": round(gap, 3),
        "greedy_rounds": g.rounds,
        "batched_rounds": b.rounds,
        "greedy_sched_s": round(g.sched_time_s, 4),
        "batched_sched_s": round(b.sched_time_s, 4),
        "unplaced_greedy": g.unplaced,
        "unplaced_batched": b.unplaced,
    }


def make_workload(
    rng: np.random.Generator,
    n_nodes: int,
    n_classes: int,
    n_tasks: int,
    r_dim: int = 16,
    heterogeneous: bool = True,
    gpu_frac: float = 0.0,
    custom_frac: float = 0.0,
    load_factor: float = 0.8,
    dur_range: Tuple[int, int] = (1, 20),
    target_waves: Optional[float] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[np.ndarray]]:
    """Synthetic cluster + class-grouped workload generator shared by the
    benchmark configs (BASELINE.json configs 1-3) and tests.

    Column convention matches resources.PREDEFINED_RESOURCES:
    0=CPU, 1=GPU, 2=TPU, 3=memory; columns >=5 are custom resources.
    """
    total = np.zeros((n_nodes, r_dim), np.float32)
    if heterogeneous:
        total[:, 0] = rng.integers(16, 129, n_nodes)
        total[:, 3] = rng.integers(64, 513, n_nodes)
    else:
        total[:, 0] = 64.0
        total[:, 3] = 256.0
    if gpu_frac > 0:
        has_gpu = rng.random(n_nodes) < gpu_frac
        total[has_gpu, 1] = rng.choice([4.0, 8.0], int(has_gpu.sum()))
    if custom_frac > 0:
        has_c = rng.random(n_nodes) < custom_frac
        total[has_c, 5] = 16.0
    alive = np.ones(n_nodes, bool)

    demands = np.zeros((n_classes, r_dim), np.float32)
    demands[:, 0] = rng.integers(1, 5, n_classes)
    mem_heavy = rng.random(n_classes) < 0.4
    demands[mem_heavy, 3] = rng.integers(1, 9, int(mem_heavy.sum()))
    if gpu_frac > 0:
        gpu_c = rng.random(n_classes) < 0.2
        demands[gpu_c, 1] = rng.integers(1, 3, int(gpu_c.sum()))
    if custom_frac > 0:
        cus = rng.random(n_classes) < 0.15
        demands[cus, 5] = 1.0
    counts = rng.multinomial(
        n_tasks, np.ones(n_classes) / n_classes
    ).astype(np.int32)

    # With target_waves set, rescale CPU capacity so the workload needs about
    # that many full waves through the cluster (contention is what makes
    # makespan differences visible; a single-wave run measures nothing).
    if target_waves is not None:
        cpu_demand = float((demands[:, 0] * counts).sum())
        want_capacity = cpu_demand / (load_factor * target_waves)
        scale = want_capacity / max(float(total[:, 0].sum()), 1.0)
        total[:, 0] = np.maximum(np.round(total[:, 0] * scale), 4.0)
    durations = [
        rng.integers(dur_range[0], dur_range[1] + 1, int(k)).astype(np.int64)
        for k in counts
    ]
    return total, alive, demands, counts, durations
