"""Placement-group bundle packing: STRICT_PACK / PACK / SPREAD / STRICT_SPREAD.

Reference: src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc
(BundlePackSchedulingPolicy etc., node scoring via LeastResourceScorer) driven
by src/ray/gcs/gcs_server/gcs_placement_group_scheduler.cc. Semantics are
all-or-nothing: either every bundle gets a node or the PG fails this round
(the 2PC prepare/commit against node daemons lives in the control plane, not
here — this module is the pure packing math).

STRICT_PACK reduces to a single summed demand, which lets many PGs be packed
as one batched-kernel call (`strict_pack_batch`) — the vectorized bin-packing
path of BASELINE.json config 4.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ray_tpu.sched import kernel_np
from ray_tpu.sched.kernel_np import EPS


def _least_resource_score(avail_after: np.ndarray, total: np.ndarray) -> np.ndarray:
    """Best-fit score per node: mean remaining fraction after placement —
    lower is better (reference: LeastResourceScorer::Score, which rewards
    nodes left with the least slack)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(total > 0, avail_after / np.maximum(total, EPS), 0.0)
    denom = np.maximum((total > 0).sum(axis=1), 1)
    return (frac.sum(axis=1) / denom).astype(np.float32)


def schedule_bundles(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    bundles: np.ndarray,
    strategy: str = "PACK",
) -> Tuple[Optional[np.ndarray], np.ndarray]:
    """Place one PG's bundles[B, R]. Returns (nodes[B] int32 or None on
    failure, new availability). All-or-nothing."""
    avail = avail.astype(np.float32).copy()
    B = bundles.shape[0]
    N = avail.shape[0]
    out = np.full(B, -1, dtype=np.int32)

    if strategy == "STRICT_PACK":
        demand = bundles.sum(axis=0)
        feas = kernel_np.feasible_mask(avail, alive, demand)
        if not feas.any():
            return None, avail
        score = _least_resource_score(avail - demand[None, :], total)
        score = np.where(feas, score, np.float32(np.inf))
        n = int(np.argmin(score))
        out[:] = n
        avail[n] = np.maximum(avail[n] - demand, 0.0)
        return out, avail

    used_nodes = np.zeros(N, dtype=bool)
    # Larger bundles first so best-fit has room to work (stable within ties).
    order = np.argsort(-bundles.sum(axis=1), kind="stable")
    for b in order:
        d = bundles[b]
        feas = kernel_np.feasible_mask(avail, alive, d)
        if strategy == "STRICT_SPREAD":
            feas = feas & ~used_nodes
        if not feas.any():
            return None, avail
        score = _least_resource_score(avail - d[None, :], total)
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            # Prefer unused nodes; among them spread by *most* slack.
            score = -score
            if strategy == "SPREAD" and (feas & ~used_nodes).any():
                feas = feas & ~used_nodes
        score = np.where(feas, score, np.float32(np.inf))
        n = int(np.argmin(score))
        out[b] = n
        used_nodes[n] = True
        avail[n] = np.maximum(avail[n] - d, 0.0)
    return out, avail


def strict_pack_batch(
    avail: np.ndarray,
    total: np.ndarray,
    alive: np.ndarray,
    pg_demands: np.ndarray,
    backend: str = "numpy",
) -> Tuple[np.ndarray, np.ndarray]:
    """Place many STRICT_PACK PGs at once: pg_demands[P, R] are summed bundle
    demands; each PG is a scheduling class with count 1, so this is exactly
    one batched-kernel call (TPU-vectorized bin-packing, config 4).

    Returns (node[P] int32 or -1, new availability)."""
    P = pg_demands.shape[0]
    counts = np.ones(P, dtype=np.int32)
    if backend == "jax":
        from ray_tpu.sched import kernel_jax
        import jax.numpy as jnp

        pad = kernel_jax.bucket_size(P)
        d, k = kernel_jax.pad_problem(pg_demands.astype(np.float32), counts, pad)
        assigned, new_avail = kernel_jax.schedule_classes(
            jnp.asarray(avail, jnp.float32), jnp.asarray(total, jnp.float32),
            jnp.asarray(alive), jnp.asarray(d), jnp.asarray(k),
        )
        assigned = np.asarray(assigned[:P])
        new_avail = np.asarray(new_avail)
    else:
        assigned, new_avail = kernel_np.schedule_classes(
            avail, total, alive, pg_demands.astype(np.float32), counts
        )
    nodes = np.where(
        assigned.sum(axis=1) > 0, assigned.argmax(axis=1), -1
    ).astype(np.int32)
    return nodes, new_avail
