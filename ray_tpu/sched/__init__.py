"""Scheduling layer: the batched-assignment reformulation of Ray's schedulers.

The reference implements cluster-level placement as per-task C++ loops:
- raylet hot path: src/ray/raylet/scheduling/cluster_resource_scheduler.cc
  (ClusterResourceScheduler::GetBestSchedulableNode) dispatching to
  src/ray/raylet/scheduling/policy/*.cc per-request policies;
- GCS placement groups: src/ray/gcs/gcs_server/gcs_placement_group_scheduler.cc
  over policy/bundle_scheduling_policy.cc;
- autoscaler bin-packing: python/ray/autoscaler/_private/resource_demand_scheduler.py.

Here all three consume the same kernel: pending work is grouped into
*scheduling classes* (identical resource-demand vectors — the same notion the
reference's NormalTaskSubmitter uses for lease reuse, see
src/ray/core_worker/transport/normal_task_submitter.cc), producing a
[classes x nodes] assignment-count problem solved by vectorized scoring —
NumPy on CPU, identical math under jax.jit on TPU.
"""

from ray_tpu.sched.resources import (
    PREDEFINED_RESOURCES,
    ResourceSpace,
    NodeResourceState,
    pack_demands,
)
from ray_tpu.sched.policy import (
    SchedulingPolicy,
    HybridPolicy,
    SpreadPolicy,
    NodeAffinityPolicy,
    make_policy,
)
from ray_tpu.sched import kernel_np


def __getattr__(name):
    # kernel_jax is imported lazily so the pure-NumPy policy path (the CPU
    # fallback) never requires jax at import time.
    if name == "kernel_jax":
        import ray_tpu.sched.kernel_jax as m

        return m
    raise AttributeError(name)

__all__ = [
    "PREDEFINED_RESOURCES",
    "ResourceSpace",
    "NodeResourceState",
    "pack_demands",
    "SchedulingPolicy",
    "HybridPolicy",
    "SpreadPolicy",
    "NodeAffinityPolicy",
    "make_policy",
    "kernel_np",
]
