"""``python -m ray_tpu`` — the CLI entry point (reference: the `ray` CLI,
python/ray/scripts/scripts.py)."""

from ray_tpu.scripts.cli import main

main()
