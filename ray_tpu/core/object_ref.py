"""ObjectRef: a future naming an object in the distributed store.

Reference: ObjectRef in python/ray/includes/object_ref.pxi / the ObjectID in
src/ray/common/id.h. IDs here are 16-byte random (task-output ids are derived
deterministically from task id + output index, mirroring
ObjectID::FromIndex).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
from typing import Optional


def _rand_hex(n: int = 16) -> str:
    return os.urandom(n).hex()


# Thread-local construction hook: while active, every ObjectRef built on this
# thread (including via unpickling) is reported to the callback. This is how
# refs NESTED inside values are discovered — at serialize time on the owner
# (so they join the task's deps and get pinned) and at deserialize time in
# the worker (so the worker registers as a borrower). Reference analog: the
# serialization hooks feeding reference_count.cc's AddNestedObjectIds /
# AddBorrowedObject.
_capture = threading.local()


@contextlib.contextmanager
def capture_refs(cb):
    prev = getattr(_capture, "cb", None)
    _capture.cb = cb
    try:
        yield
    finally:
        _capture.cb = prev


class ObjectRef:
    __slots__ = ("id", "owner", "task_id", "_hash", "_on_del")

    def __init__(self, id: Optional[str] = None, owner: Optional[str] = None,
                 task_id: Optional[str] = None):
        self.id = id or _rand_hex()
        self.owner = owner  # owner worker/driver id (ownership-based directory)
        self.task_id = task_id  # creating task, for lineage reconstruction
        self._hash = hash(self.id)
        cb = getattr(_capture, "cb", None)
        if cb is not None:
            cb(self)

    def _register(self, on_del) -> bool:
        """Runtime hook: count this instance toward the owner's local
        refcount; its deletion decrements (reference: reference_count.cc
        AddLocalReference / the Cython __dealloc__ path). Returns False if
        already registered (never double-count one instance)."""
        if getattr(self, "_on_del", None) is not None:
            return False
        self._on_del = on_del
        return True

    def __del__(self):
        cb = getattr(self, "_on_del", None)
        if cb is not None:
            try:
                cb(self.id)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass

    @staticmethod
    def for_task_output(task_id: str, index: int, owner: Optional[str] = None) -> "ObjectRef":
        oid = hashlib.sha1(f"{task_id}:{index}".encode()).hexdigest()[:32]
        return ObjectRef(oid, owner=owner, task_id=task_id)

    def hex(self) -> str:
        return self.id

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self.id == other.id

    def __repr__(self):
        return f"ObjectRef({self.id[:16]})"

    def __reduce__(self):
        # fires the capture hook at SERIALIZE time too, so an owner pickling
        # a value discovers the refs nested in it (deserialize-side capture
        # goes through __init__)
        cb = getattr(_capture, "cb", None)
        if cb is not None:
            cb(self)
        return (ObjectRef, (self.id, self.owner, self.task_id))
