"""Config/flag system.

Reference: src/ray/common/ray_config_def.h — a single X-macro list
``RAY_CONFIG(type, name, default)`` with env override ``RAY_<name>`` and
``ray.init(_system_config={...})``. Same model here: one declarative table,
env override ``RAY_TPU_<name>``, programmatic override via
``ray_tpu.init(_system_config=...)``.
"""

from __future__ import annotations

import os
from typing import Any, Dict

# name -> (type, default)  — keep scheduler knobs named like the reference's
# (scheduler_spread_threshold etc. in ray_config_def.h) for discoverability.
_DEFS: Dict[str, tuple] = {
    "scheduler_spread_threshold": (float, 0.5),
    "scheduler_top_k_fraction": (float, 0.2),  # reserved; kernel is deterministic
    "scheduling_policy": (str, "hybrid"),  # hybrid | jax_tpu | spread | random
    "scheduler_kernel_algo": (str, "scan"),  # "scan" | "rounds" | "chunked"
    # jax_tpu policy: rounds smaller than this many classes*nodes cells run
    # on the bit-identical NumPy twin (device dispatch latency dominates
    # small solves); 0 = always use the device
    "jax_policy_min_cells": (int, 262_144),
    # device rounds in flight before the oldest is forced: deep pipelining
    # amortizes per-dispatch link latency (~67ms/sync on a degraded axon
    # tunnel vs ~5ms/round for 16 chained enqueues). 0 = synchronous
    # rounds (old behavior)
    "jax_policy_pipeline_depth": (int, 8),
    # how long the dep gate honors an owner's "my in-flight actor call will
    # produce this object" voucher before node-death sweeps may re-evaluate
    # the dep (guards against owners that die/fail to publish an error)
    "own_inflight_lease_s": (float, 600.0),
    "scheduler_round_interval_ms": (float, 2.0),
    "max_direct_call_object_size": (int, 100 * 1024),  # inline-in-reply threshold
    "worker_lease_timeout_ms": (float, 500.0),
    "task_max_retries": (int, 3),
    "actor_max_restarts": (int, 0),
    "health_check_period_ms": (float, 1000.0),
    "health_check_timeout_ms": (float, 5000.0),
    "object_store_memory_bytes": (int, 256 * 1024 * 1024),
    "object_spilling_dir": (str, ""),  # empty -> <session_dir>/spill
    "object_transfer_chunk_bytes": (int, 1024 * 1024),
    # concurrent big-object pulls per peer daemon; more pulls queue behind a
    # semaphore (reference: pull_manager.cc prioritized, bandwidth-bounded
    # pull bundles)
    "object_pull_max_concurrent": (int, 2),
    # in-flight chunk requests per pull (pipelining window)
    "object_pull_window": (int, 8),
    # daemon-side arg prefetch bound; short on purpose — on failure the task
    # returns to the GCS dependency gate, which holds it until the object
    # actually exists (so slow producers don't need a long timeout here)
    "object_fetch_timeout_s": (float, 10.0),
    "memory_monitor_interval_ms": (float, 500.0),
    "gcs_port": (int, 0),  # 0 -> pick free port
    # outage window before RetryingRpcClient fires on_reconnect_timeout
    # (drivers fail stranded tasks then) — reconnection itself keeps
    # retrying past it, so a GCS back after minutes still restores the
    # session (reference: gcs_rpc_server_reconnect_timeout_s)
    "gcs_reconnect_timeout_s": (float, 30.0),
    # --- rpc layer (cluster/rpc.py; reference: the grpc deadline/retry
    # knobs around retryable_grpc_client.cc) ---
    "rpc_call_timeout_s": (float, 30.0),  # default blocking-call deadline
    # per-frame socket send deadline: a peer that stops draining its
    # receive buffer wedges senders at most this long (then ConnectionLost)
    "rpc_send_timeout_s": (float, 30.0),
    "rpc_server_start_timeout_s": (float, 10.0),
    "rpc_server_stop_timeout_s": (float, 3.0),
    # RetryingRpcClient backoff: full jitter over
    # [0, min(max_backoff, base * 2^attempt)]
    "rpc_retry_base_backoff_s": (float, 0.05),
    "rpc_retry_max_backoff_s": (float, 2.0),
    # sub-deadline per retryable attempt (a lost frame costs one attempt
    # window, not the whole call budget)
    "rpc_retry_attempt_timeout_s": (float, 5.0),
    # --- compiled execution graphs (ray_tpu/dag/) ---
    # initial payload area per edge channel; channels grow in place (the
    # writer ftruncates + remaps) when a frame exceeds it
    "dag_channel_buffer_bytes": (int, 65536),
    # default per-iteration deadline for CompiledDAG.execute — bounds every
    # channel wait so a dead pipeline raises instead of parking forever
    "dag_execute_timeout_s": (float, 60.0),
    # --- serve fast path (ray_tpu/serve/fastpath.py): the zero-RPC request
    # plane over dag-style shm channel pairs ---
    # initial payload area per request/response channel (grow-in-place)
    "serve_fastpath_channel_bytes": (int, 65536),
    # continuous batcher: hard cap on one dispatch group
    "serve_fastpath_batch_max": (int, 64),
    # target end-to-end latency the adaptive batch sizer aims at: batch
    # size ~= target / EMA(per-item service time), clamped to batch_max
    "serve_fastpath_target_latency_s": (float, 0.02),
    # router membership refresh cadence (a BACKGROUND thread, so the
    # steady-state request path stays RPC-free; failures force a refresh)
    "serve_fastpath_refresh_s": (float, 1.0),
    # router saturation bound: with every replica pair at >= this many
    # locally-observed in-flight requests, submit fails FAST with
    # ClusterOverloadedError instead of queueing behind the backlog;
    # 0 = unbounded (no fail-fast)
    "serve_fastpath_max_inflight": (int, 0),
    # --- overload control plane (admission + backpressure; see README
    # "Overload control") ---
    # GCS admission controller: max in-system (queued + dep-waiting +
    # running) normal tasks per driver; 0 disables admission control.
    # Over the bound, submit_task returns a typed retryable rejection
    # (ClusterOverloadedError client-side) — never a silent drop
    "admission_max_pending_per_driver": (int, 0),
    # pacing hint attached to admission rejections and overload pushes
    "admission_retry_after_s": (float, 0.25),
    # client-side pacing: retry rejected admissions (and slow submitters
    # down while the GCS advertises overload) instead of failing fast
    "admission_pacing_enabled": (bool, True),
    # total budget a rejected task may spend re-attempting admission
    # before its refs fail with ClusterOverloadedError
    "admission_pacing_max_s": (float, 10.0),
    # cluster overload state (hysteresis, derived each scheduler round
    # from GCS queue depth + daemon-reported queue depths): overloaded
    # when queued tasks exceed high*total_CPUs, cleared below low*CPUs
    "overload_pending_high_per_cpu": (float, 8.0),
    "overload_pending_low_per_cpu": (float, 2.0),
    # --- gray-failure defense plane (health scoring + straggler
    # speculation + quarantine; see README "Gray-failure defense") ---
    # master switch for the whole plane (scoring always runs; this gates
    # speculation + quarantine ACTIONS so the A/B storm can compare arms)
    "gray_defense_enabled": (bool, True),
    # straggler speculation: a RUNNING task whose elapsed time exceeds
    # factor * p95(its class's observed durations) gets a speculative
    # duplicate on a healthier node; 0 disables speculation
    "speculation_quantile_factor": (float, 3.0),
    # total executions per task including the primary (2 = at most one
    # speculative copy)
    "speculation_max_copies": (int, 2),
    # duration samples a class needs before its p95 is trusted
    "speculation_min_samples": (int, 5),
    # elapsed-time floor before any task is speculation-eligible (guards
    # sub-millisecond classes against scheduler-jitter false positives)
    "speculation_min_elapsed_s": (float, 0.2),
    # node suspicion hysteresis (score in [0,1] from heartbeat jitter +
    # per-(func,node) duration EMAs): sustained >= high quarantines,
    # probe-verified < low returns the node to service via probation
    "quarantine_high": (float, 0.7),
    "quarantine_low": (float, 0.3),
    # consecutive health sweeps over quarantine_high before quarantine
    # actually triggers ("sustained", not a single bad sample)
    "quarantine_sustain_sweeps": (int, 3),
    # cadence of probe pushes to quarantined nodes (probe results feed
    # recovery; 0 disables probing, leaving quarantine sticky)
    "probe_interval_s": (float, 2.0),
    # health sweeps a PROBATION node must stay clean before full OK;
    # a relapse (score >= high) during probation re-quarantines instantly
    "probation_sweeps": (int, 3),
    "num_workers_soft_limit": (int, 0),  # 0 -> num_cpus
    "worker_start_timeout_s": (float, 30.0),
    "metrics_report_interval_ms": (float, 2000.0),
    # --- observability (ray_tpu.obs; util/metrics.py pipeline) ---
    # master switch for metric collection + the heartbeat delta export;
    # instrumented hot paths check util.metrics.ENABLED (one global load)
    "metrics_enabled": (bool, True),
    # always-on in-memory flight recorder (ray_tpu/obs/flightrec.py):
    # a bounded ring of the same events the ProtocolTracer emits, dumped
    # to artifacts/flightrec-*.jsonl on crash surfaces; cheap enough to
    # leave ON (preformatted tuples, no serialization until a dump)
    "flight_recorder_enabled": (bool, True),
    "flight_recorder_cap": (int, 4096),
    "log_to_driver": (bool, True),
    "session_dir_root": (str, "/tmp/ray_tpu"),
    # task-event log (reference: gcs_task_manager.cc
    # RAY_task_events_max_num_task_in_gcs): recent window kept in memory;
    # everything beyond it aggregates + spills to JSONL so 1M-task runs
    # keep a queryable timeline without unbounded RSS
    "task_events_recent_cap": (int, 10_000),
    "task_events_spill": (bool, True),
    # anonymized local usage recording (util/usage.py); opt out with
    # RAY_TPU_usage_stats_enabled=0 (reference: RAY_USAGE_STATS_ENABLED)
    "usage_stats_enabled": (bool, True),
}


class Config:
    def __init__(self, overrides: Dict[str, Any] | None = None):
        self._values: Dict[str, Any] = {}
        for name, (typ, default) in _DEFS.items():
            env = os.environ.get(f"RAY_TPU_{name}")
            if env is not None:
                self._values[name] = _parse(typ, env)
            else:
                self._values[name] = default
        for k, v in (overrides or {}).items():
            if k not in _DEFS:
                raise ValueError(f"unknown config key {k!r}")
            self._values[k] = _parse(_DEFS[k][0], v)

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


def _parse(typ, val):
    if typ is bool and isinstance(val, str):
        return val.lower() in ("1", "true", "yes", "on")
    return typ(val)


GLOBAL_CONFIG = Config()


def set_global_config(overrides: Dict[str, Any] | None) -> Config:
    global GLOBAL_CONFIG
    GLOBAL_CONFIG = Config(overrides)
    return GLOBAL_CONFIG
