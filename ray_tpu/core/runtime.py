"""Local-mode runtime: tasks, actors, and objects in one process.

This is the core-worker-equivalent (reference: src/ray/core_worker/
core_worker.cc — SubmitTask/ExecuteTask/Get/Put) for a single node: worker
threads instead of worker processes, the in-process MemoryStore as the object
store, and the *real* batched scheduling kernel in the loop — the same
policy/kernel path the multi-node control plane uses, so scheduling semantics
don't fork between modes.

Threading model: a scheduler thread runs batched rounds (reference hot loop:
ClusterTaskManager::ScheduleAndDispatchTasks, cluster_task_manager.cc);
execution runs on a thread pool gated by resource accounting, not pool size;
each actor gets a dedicated mailbox thread (per-caller FIFO ordering —
reference: actor_submit_queue.h). Workers that block in get() release their
resources while blocked (reference: CoreWorker::NotifyDirectCallTaskBlocked).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from collections import defaultdict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.config import Config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    TaskError,
)
from ray_tpu.core.memory_store import MemoryStore
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import TaskSpec, new_id
from ray_tpu.sched.policy import make_policy_from_config
from ray_tpu.sched.resources import NodeResourceState, ResourceSpace
from ray_tpu.util.task_events import TaskEventLog

_context = threading.local()


def _env_stepped(gen, _rtenv, env):
    """Re-enter the (process-global) runtime env around each production
    step of a local-mode streaming generator, so the env lock is held
    only while user code actually runs — never across backpressure
    parking."""
    env_vars, cwd, py_paths = env
    while True:
        with _rtenv.applied(env_vars, cwd, py_paths=py_paths):
            try:
                item = next(gen)
            except StopIteration:
                return
        yield item




class _ActorState:
    def __init__(self, actor_id: str, node_idx: int, demand: np.ndarray):
        self.actor_id = actor_id
        self.node_idx = node_idx
        self.demand = demand
        self.mailbox: deque = deque()
        self.cv = threading.Condition()
        self.instance = None
        self.dead = False
        self.death_cause: Optional[str] = None
        self.thread: Optional[threading.Thread] = None
        self.num_restarts = 0
        self.aio = None  # ActorEventLoop when the class has async methods


class LocalRuntime:
    """One-process cluster: single scheduling node, thread workers."""

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        config: Optional[Config] = None,
    ):
        self.config = config or Config()
        self.node_id = new_id("node")
        self.worker_id = new_id("driver")
        num_cpus = num_cpus if num_cpus is not None else (os.cpu_count() or 4)
        res = {"CPU": float(num_cpus), "memory": float(2**33)}
        res.update(resources or {})
        self.space = ResourceSpace()
        self.state = NodeResourceState(space=self.space)
        self.state.add_node(self.node_id, res)
        self.store = MemoryStore()
        self.policy = make_policy_from_config(self.config)

        self._lock = threading.Lock()
        self._pending: deque = deque()  # schedulable TaskSpecs
        self._waiting: Dict[str, Tuple[TaskSpec, set]] = {}  # task_id -> (spec, missing oids)
        self._dep_index: Dict[str, List[str]] = defaultdict(list)  # oid -> task_ids
        self._infeasible: deque = deque()
        self._running: Dict[str, TaskSpec] = {}
        self._actors: Dict[str, _ActorState] = {}
        self._pgs: Dict[str, dict] = {}
        self._streams: Dict[str, dict] = {}  # task_id -> backpressure state
        # timeline (ray timeline equivalent): same bounded-memory backend
        # as the GCS — recent window + incremental aggregates + anonymous
        # JSONL spill (removed on shutdown) so 1M-task local runs keep a
        # full queryable timeline without unbounded RSS
        self._task_events = TaskEventLog(
            recent_cap=self.config.task_events_recent_cap,
            anonymous_spill=self.config.task_events_spill,
        )
        # internal KV (reference: GCS internal kv, _internal_kv_put — backs
        # named actors, collective group rendezvous, serve state)
        self._kv: Dict[str, bytes] = {}

        # Local mode shares one jax runtime across all worker THREADS (unlike
        # cluster mode's worker processes). First-time backend init is not
        # thread-safe with PJRT plugin registration (the axon plugin races:
        # "Unable to initialize backend 'axon'... not in known backends"), so
        # force it once, serially, before any worker thread can.
        if not os.environ.get("RAY_TPU_SKIP_JAX_INIT"):
            try:
                import jax

                jax.devices()
            except Exception:
                pass  # no usable backend; user code will surface its own error

        self._sched_cv = threading.Condition()
        self._stopped = False
        self._executor = ThreadPoolExecutor(
            max_workers=max(int(num_cpus) * 4, 16), thread_name_prefix="raytpu-worker"
        )
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="raytpu-sched", daemon=True
        )
        self._sched_thread.start()

    # ------------------------------------------------------------------ submit

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [
            ObjectRef.for_task_output(spec.task_id, i, owner=self.worker_id)
            for i in range(spec.num_returns)
        ]
        if spec.actor_creation:
            # Register the mailbox immediately so method calls submitted
            # before the creation task is scheduled queue up instead of
            # failing (reference: the GCS actor table exists from
            # registration, gcs_actor_manager.cc).
            with self._lock:
                self._actors[spec.actor_id] = _ActorState(spec.actor_id, 0, None)
        ready = False
        with self._lock:
            missing = {
                a.id
                for a in list(spec.args) + list(spec.kwargs.values())
                if isinstance(a, ObjectRef) and not self.store.contains(a)
            }
            if missing:
                self._waiting[spec.task_id] = (spec, missing)
                for oid in missing:
                    self._dep_index[oid].append(spec.task_id)
            else:
                ready = True
        if ready:
            self._make_ready(spec)
        else:
            # Close the submit/complete race: a dependency may have landed
            # between the contains() check and registration above — re-check
            # and fire the ready path for anything now present.
            for oid in list(missing):
                if self.store.contains(ObjectRef(oid)):
                    self._on_object_ready(ObjectRef(oid))
        self._kick()
        return refs

    def _make_ready(self, spec: TaskSpec):
        """Route a dependency-ready task: actor method calls bypass the
        scheduler and go straight to the actor's mailbox (reference: actor
        calls skip the raylet, actor_task_submitter.cc); everything else
        queues for the batched scheduling round."""
        if spec.actor_id is not None and not spec.actor_creation:
            with self._lock:
                self._running[spec.task_id] = spec
            self._enqueue_actor_task(spec)
        else:
            with self._lock:
                self._pending.append(spec)

    def _kick(self):
        with self._sched_cv:
            self._sched_cv.notify()

    def _on_object_ready(self, ref: ObjectRef):
        newly_ready = []
        with self._lock:
            for tid in self._dep_index.pop(ref.id, []):
                entry = self._waiting.get(tid)
                if entry is None:
                    continue
                spec, missing = entry
                missing.discard(ref.id)
                if not missing:
                    del self._waiting[tid]
                    newly_ready.append(spec)
        for spec in newly_ready:
            self._make_ready(spec)
        if newly_ready:
            self._kick()

    # --------------------------------------------------------------- scheduler

    def _scheduler_loop(self):
        interval = self.config.scheduler_round_interval_ms / 1000.0
        while not self._stopped:
            with self._sched_cv:
                self._sched_cv.wait(timeout=interval)
            try:
                self._schedule_round()
            except Exception:  # pragma: no cover - keep the loop alive
                traceback.print_exc()

    def _schedule_round(self):
        """One batched round: group pending by scheduling class, run the
        policy kernel, dispatch. Reference: ScheduleAndDispatchTasks."""
        self._retry_pending_pgs_local()
        with self._lock:
            if not self._pending and not self._infeasible:
                return
            batch = list(self._pending) + list(self._infeasible)
            self._pending.clear()
            self._infeasible.clear()

        rest = []
        for spec in batch:
            if spec.strategy.kind == "PLACEMENT_GROUP":
                # tasks ride inside their bundle's reservation (zero extra
                # demand once the PG is placed)
                pg = self._pgs.get(spec.strategy.placement_group_id)
                if pg is None:
                    # nonexistent/removed PG can never become schedulable
                    self._store_error(spec, TaskError(
                        f"placement group {spec.strategy.placement_group_id} "
                        f"does not exist"))
                    with self._lock:
                        self._running.pop(spec.task_id, None)
                elif pg["state"] == "CREATED":
                    self._dispatch(spec, 0, self.space.vector({}))
                else:
                    with self._lock:
                        self._infeasible.append(spec)
            else:
                rest.append(spec)
        batch = rest
        if not batch:
            return

        classes: Dict[Tuple, List[TaskSpec]] = defaultdict(list)
        for spec in batch:
            classes[spec.scheduling_class()].append(spec)
        keys = list(classes.keys())
        demands = np.stack(
            [self.space.vector(classes[k][0].resources) for k in keys]
        )
        counts = np.array([len(classes[k]) for k in keys], dtype=np.int32)

        with self._lock:
            assigned = self.policy.schedule(self.state, demands, counts)

        for c, key in enumerate(keys):
            specs = classes[key]
            placed = int(assigned[c].sum())
            for spec, _ in zip(specs, range(placed)):
                node_idx = 0  # single node in local mode
                self._dispatch(spec, node_idx, demands[c])
            for spec in specs[placed:]:
                with self._lock:
                    self._infeasible.append(spec)

    def _retry_pending_pgs_local(self):
        from ray_tpu.sched.bundles import schedule_bundles

        for pg in list(self._pgs.values()):
            if pg["state"] != "PENDING":
                continue
            with self._lock:
                mat = np.stack([self.space.vector(b) for b in pg["bundles"]])
                nodes, new_avail = schedule_bundles(
                    self.state.available, self.state.total, self.state.alive,
                    mat, strategy=pg["strategy"],
                )
                if nodes is not None:
                    self.state.available = new_avail
                    pg["state"] = "CREATED"
                    pg["nodes"] = [self.state.node_ids[i] for i in nodes]

    def _dispatch(self, spec: TaskSpec, node_idx: int, demand: np.ndarray):
        with self._lock:
            self._running[spec.task_id] = spec
        if spec.actor_creation:
            self._start_actor(spec, node_idx, demand)
        else:
            self._executor.submit(self._run_task, spec, node_idx, demand)

    # --------------------------------------------------------------- execution

    def _resolve_args(self, spec: TaskSpec):
        entries = {}
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                e = self.store.try_get(a)
                if e is None:
                    raise RuntimeError(f"dependency {a} not ready at dispatch")
                if e.is_exception:
                    raise e.value if isinstance(e.value, BaseException) else TaskError(str(e.value))
                entries[a.id] = e.value
        args = tuple(entries[a.id] if isinstance(a, ObjectRef) else a for a in spec.args)
        kwargs = {
            k: (entries[v.id] if isinstance(v, ObjectRef) else v)
            for k, v in spec.kwargs.items()
        }
        return args, kwargs

    # ------------------------------------------------- streaming generators
    # (reference: _raylet.pyx streaming generator returns; protocol in
    # core/generator.py — items at output indices 1..n, end marker at 0)

    def _drain_stream(self, spec: TaskSpec, gen) -> None:
        """Producer side: publish each yielded item as it is produced,
        then the end marker with the final count. A backpressure window
        parks the generator (not the scheduler) when the consumer lags."""
        from ray_tpu.core.generator import end_marker_ref, item_ref

        bp = spec.backpressure
        st = None
        if bp > 0:
            st = {"acked": 0, "cv": threading.Condition()}
            with self._lock:
                self._streams[spec.task_id] = st
        n = 0
        try:
            for value in gen:  # user errors propagate to _run_task's handler
                self.put_ref(
                    item_ref(spec.task_id, n, owner=self.worker_id), value
                )
                n += 1
                if st is not None:
                    with st["cv"]:
                        while (
                            n - st["acked"] >= bp and not self._stopped
                        ):
                            st["cv"].wait(timeout=0.5)
            self.put_ref(
                end_marker_ref(spec.task_id, owner=self.worker_id), n
            )
        finally:
            if st is not None:
                with self._lock:
                    self._streams.pop(spec.task_id, None)

    def stream_ack(self, task_id: str, consumed: int) -> None:
        """Consumer handed out items [0, consumed): widen the window."""
        with self._lock:
            st = self._streams.get(task_id)
        if st is not None:
            with st["cv"]:
                st["acked"] = max(st["acked"], consumed)
                st["cv"].notify_all()

    def stream_item_ready(self, ref: ObjectRef) -> bool:
        return self.store.contains(ref)

    def stream_read_end(self, ref: ObjectRef):
        """(value, is_exception) of the end marker, without raising."""
        e = self.store.get([ref], timeout=1.0)[0]
        return e.value, e.is_exception

    def stream_wait_any(self, refs, timeout: float) -> None:
        self.store.wait(refs, 1, timeout)

    def _store_results(self, spec: TaskSpec, value: Any):
        refs = [
            ObjectRef.for_task_output(spec.task_id, i, owner=self.worker_id)
            for i in range(spec.num_returns)
        ]
        if spec.num_returns == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} returned {len(values)} values, "
                    f"expected num_returns={spec.num_returns}"
                )
        for ref, v in zip(refs, values):
            self.put_ref(ref, v)

    def _store_error(self, spec: TaskSpec, err: BaseException):
        for i in range(spec.num_returns):
            ref = ObjectRef.for_task_output(spec.task_id, i, owner=self.worker_id)
            self.put_ref(ref, err, is_exception=True)

    def _run_task(self, spec: TaskSpec, node_idx: int, demand: np.ndarray):
        _context.task = spec
        _context.node_idx = node_idx
        _context.demand = demand
        _context.blocked_released = False
        start = time.time()
        try:
            args, kwargs = self._resolve_args(spec)
            from ray_tpu.core import runtime_env as _rtenv

            re = spec.runtime_env or {}
            env = (
                re.get("env_vars"), re.get("working_dir"),
                _rtenv.local_py_paths(re, self.config.session_dir_root),
            )
            with _rtenv.applied(env[0], env[1], py_paths=env[2]):
                value = spec.func(*args, **kwargs)
                if spec.streaming and not hasattr(value, "__next__"):
                    raise TypeError(
                        "num_returns='streaming' requires a generator "
                        f"function; {spec.name} returned {type(value)}"
                    )
                if not spec.streaming:
                    self._store_results(spec, value)
            if spec.streaming:
                # drain OUTSIDE the applied() context: it holds the
                # process-global env lock, and a backpressured stream can
                # park indefinitely — which would deadlock every other
                # runtime_env task in local mode. Instead each production
                # step re-enters the env around next() (user code still
                # runs under its env; the lock is released while parked).
                gen = value
                if any(env):
                    gen = _env_stepped(value, _rtenv, env)
                self._drain_stream(spec, gen)
            status = "FINISHED"
        except BaseException as e:
            if spec.retries_left > 0 and not isinstance(e, TaskError):
                spec.retries_left -= 1
                with self._lock:
                    self._running.pop(spec.task_id, None)
                    self._pending.append(spec)
                self._release_resources(node_idx, demand)
                self._kick()
                _context.task = None
                return
            tb = traceback.format_exc()
            self._store_error(
                spec, TaskError(f"task {spec.name or spec.task_id} failed: {e!r}", tb)
            )
            status = "FAILED"
        finally:
            _context.task = None
        with self._lock:
            self._running.pop(spec.task_id, None)
        if not getattr(_context, "blocked_released", False):
            self._release_resources(node_idx, demand)
        self._task_events.append(
            {
                "task_id": spec.task_id,
                "name": spec.name,
                "start": start,
                "end": time.time(),
                "status": status,
                "node": self.node_id,
            }
        )
        self._kick()

    # ------------------------------------------------------------------ actors

    def _release_resources(self, node_idx: int, demand) -> None:
        """All resource mutations serialize on self._lock with the scheduler's
        copy-compute-replace round, else releases landing mid-round are lost."""
        if demand is None:
            return
        with self._lock:
            self.state.release(node_idx, demand)

    def _fail_actor(self, st: _ActorState, creation_spec: Optional[TaskSpec]):
        """Resolve every ref tied to a dead actor so no caller hangs: the
        creation ref (if the ctor never ran/finished) and all queued calls."""
        err = ActorDiedError(
            f"actor {st.actor_id} is dead: {st.death_cause or 'killed'}"
        )
        if creation_spec is not None:
            self._store_error(creation_spec, err)
            with self._lock:
                self._running.pop(creation_spec.task_id, None)
        with st.cv:
            pending = list(st.mailbox)
            st.mailbox.clear()
        for spec in pending:
            self._store_error(spec, err)
            with self._lock:
                self._running.pop(spec.task_id, None)

    def _start_actor(self, spec: TaskSpec, node_idx: int, demand: np.ndarray):
        with self._lock:
            st = self._actors.get(spec.actor_id)
            if st is None:
                st = _ActorState(spec.actor_id, node_idx, demand)
                self._actors[spec.actor_id] = st
            else:
                st.node_idx = node_idx
                st.demand = demand
        if st.dead:  # killed before creation ran
            self._release_resources(node_idx, demand)
            self._fail_actor(st, creation_spec=spec)
            return
        st.thread = threading.Thread(
            target=self._actor_loop, args=(st, spec), daemon=True,
            name=f"raytpu-actor-{spec.actor_id[:8]}",
        )
        st.thread.start()

    def _actor_loop(self, st: _ActorState, creation_spec: TaskSpec):
        _context.actor_id = st.actor_id
        try:
            args, kwargs = self._resolve_args(creation_spec)
            cls = creation_spec.func
            # local mode runs actors on threads in ONE process: env applies
            # for the constructor only (not keep=) — process-global env
            # can't be owned by one thread-actor for its lifetime
            from ray_tpu.core import runtime_env as _rtenv

            re = creation_spec.runtime_env or {}
            with _rtenv.applied(
                re.get("env_vars"), re.get("working_dir"),
                py_paths=_rtenv.local_py_paths(
                    re, self.config.session_dir_root
                ),
            ):
                st.instance = cls(*args, **kwargs)
            # async actor: every method (coroutine or sync) runs on this
            # dedicated per-actor event loop (reference: python/ray/actor.py
            # async actors); max_concurrency bounds in-flight coroutines
            # via the semaphore-gated dispatch below
            from ray_tpu.core.async_actor import ActorEventLoop, class_is_async

            if class_is_async(type(st.instance)):
                st.aio = ActorEventLoop(
                    name=f"raytpu-actor-{st.actor_id[:8]}-aio"
                )
            self._store_results(creation_spec, st.actor_id)
        except BaseException as e:
            tb = traceback.format_exc()
            st.dead = True
            st.death_cause = tb
            self._store_error(
                creation_spec,
                ActorDiedError(f"actor constructor failed: {e!r}\n{tb}"),
            )
            self._release_resources(st.node_idx, st.demand)
            self._fail_actor(st, creation_spec=None)
            return
        finally:
            with self._lock:
                self._running.pop(creation_spec.task_id, None)

        # Threaded actors (reference: max_concurrency>1 runs methods on a
        # per-actor thread pool, core_worker concurrency groups): methods may
        # overlap and block on each other — needed by barrier-style actors
        # like the train report bus. Daemon threads gated by a semaphore, NOT
        # a ThreadPoolExecutor: its atexit join would deadlock interpreter
        # exit on methods blocked in a barrier that never completes.
        sem: Optional[threading.Semaphore] = None
        if creation_spec.max_concurrency > 1:
            sem = threading.Semaphore(creation_spec.max_concurrency)
        while True:
            with st.cv:
                while not st.mailbox and not st.dead:
                    st.cv.wait(timeout=0.5)
                    if self._stopped:
                        return
                if st.dead:
                    break
                spec = st.mailbox.popleft()
            if sem is None:
                self._run_actor_method(st, spec)
            else:
                sem.acquire()

                def _run(spec=spec):
                    try:
                        self._run_actor_method(st, spec)
                    finally:
                        sem.release()

                threading.Thread(
                    target=_run, daemon=True,
                    name=f"raytpu-actor-{st.actor_id[:8]}-mc",
                ).start()
        # drain mailbox with death errors; cancel in-flight coroutines so
        # dispatch threads blocked on the loop observe the death
        if st.aio is not None:
            st.aio.shutdown()
        self._fail_actor(st, creation_spec=None)
        self._release_resources(st.node_idx, st.demand)

    def _run_actor_method(self, st: _ActorState, spec: TaskSpec):
        _context.actor_id = st.actor_id
        start = time.time()
        try:
            args, kwargs = self._resolve_args(spec)
            method = getattr(st.instance, spec.method_name)
            if st.aio is not None:
                # async actor: user code runs on the actor's event loop
                # (this dispatch thread blocks as the concurrency slot)
                value = st.aio.call(method, args, kwargs)
            else:
                value = method(*args, **kwargs)
            if spec.streaming:
                if hasattr(value, "__anext__"):
                    from ray_tpu.core.async_actor import agen_to_iter

                    value = agen_to_iter(value, st.aio)
                if not hasattr(value, "__next__"):
                    raise TypeError(
                        "num_returns='streaming' requires a generator "
                        f"method; {spec.method_name} returned {type(value)}"
                    )
                self._drain_stream(spec, value)
            else:
                self._store_results(spec, value)
            status = "FINISHED"
        except BaseException as e:
            tb = traceback.format_exc()
            self._store_error(
                spec, TaskError(f"actor method {spec.method_name} failed: {e!r}", tb)
            )
            status = "FAILED"
        with self._lock:
            self._running.pop(spec.task_id, None)
        self._task_events.append(
            {
                "task_id": spec.task_id,
                "name": spec.name,
                "start": start,
                "end": time.time(),
                "status": status,
                "node": self.node_id,
                "actor_id": st.actor_id,
            }
        )

    def _enqueue_actor_task(self, spec: TaskSpec):
        # Actor method calls consume no scheduler resources; the actor holds
        # its allocation for its lifetime (reference: actor tasks bypass the
        # raylet and go straight to the actor's worker, actor_task_submitter.cc).
        st = self._actors.get(spec.actor_id)
        if st is not None:
            with st.cv:
                if not st.dead:
                    st.mailbox.append(spec)
                    st.cv.notify()
                    return
        cause = st.death_cause if st else "unknown actor"
        self._store_error(spec, ActorDiedError(f"actor {spec.actor_id} is dead: {cause}"))
        with self._lock:
            self._running.pop(spec.task_id, None)

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        return self.submit_task(spec)

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        st = self._actors.get(actor_id)
        if st is None:
            return
        with st.cv:
            st.dead = True
            st.death_cause = "ray_tpu.kill() called"
            st.cv.notify()

    # ---------------------------------------------------------------- kv store

    def kv_put(self, key: str, value):
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: str):
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str):
        with self._lock:
            self._kv.pop(key, None)

    def kv_keys(self, prefix: str = ""):
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # ----------------------------------------------------------------- objects

    def put(self, value: Any) -> ObjectRef:
        ref = ObjectRef(owner=self.worker_id)
        self.put_ref(ref, value)
        return ref

    def put_ref(self, ref: ObjectRef, value: Any, is_exception: bool = False):
        self.store.put(ref, value, is_exception)
        self._on_object_ready(ref)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        self._release_while_blocked(True)
        try:
            entries = self.store.get(refs, timeout)
        finally:
            self._release_while_blocked(False)
        out = []
        for e in entries:
            if e.is_exception:
                raise e.value if isinstance(e.value, BaseException) else TaskError(str(e.value))
            out.append(e.value)
        return out

    def wait(self, refs, num_returns=1, timeout=None):
        self._release_while_blocked(True)
        try:
            return self.store.wait(refs, num_returns, timeout)
        finally:
            self._release_while_blocked(False)

    def _release_while_blocked(self, entering: bool):
        """A worker blocking in get() releases its CPUs so siblings can run
        (reference: CoreWorker::NotifyDirectCallTaskBlocked / Unblocked)."""
        spec = getattr(_context, "task", None)
        if spec is None:
            return
        demand = getattr(_context, "demand", None)
        node_idx = getattr(_context, "node_idx", 0)
        if demand is None:
            return
        if entering:
            self._release_resources(node_idx, demand)
            _context.blocked_released = True
            self._kick()
        else:
            # Reacquire without feasibility check: temporary oversubscription
            # beats deadlock (same tradeoff the reference makes).
            with self._lock:
                self.state.available[node_idx] -= demand
            _context.blocked_released = False

    def free(self, refs: List[ObjectRef]):
        self.store.delete(refs)

    # --------------------------------------------------------- placement groups

    def create_placement_group(self, pg_id, bundles, strategy, name=""):
        """Single-node PG support (reference semantics; the multi-node path
        lives in cluster/gcs.py)."""
        from ray_tpu.sched.bundles import schedule_bundles

        with self._lock:
            mat = np.stack([self.space.vector(b) for b in bundles])
            nodes, new_avail = schedule_bundles(
                self.state.available, self.state.total, self.state.alive,
                mat, strategy=strategy,
            )
            if nodes is None:
                self._pgs[pg_id] = {"pg_id": pg_id, "state": "PENDING",
                                    "bundles": bundles, "strategy": strategy}
                return {"ok": False, "state": "PENDING"}
            self.state.available = new_avail
            self._pgs[pg_id] = {"pg_id": pg_id, "state": "CREATED",
                                "bundles": bundles, "strategy": strategy,
                                "nodes": [self.state.node_ids[i] for i in nodes]}
            return {"ok": True, "state": "CREATED"}

    def remove_placement_group(self, pg_id):
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg and pg.get("state") == "CREATED":
                for b, nid in zip(pg["bundles"], pg["nodes"]):
                    self.state.release(self.state.node_index(nid), self.space.vector(b))
        self._kick()

    def get_placement_group(self, pg_id):
        return self._pgs.get(pg_id)

    # ------------------------------------------------------------------- misc

    def cluster_resources(self) -> Dict[str, float]:
        agg: Dict[str, float] = defaultdict(float)
        for m in self.state.total_map().values():
            for k, v in m.items():
                agg[k] += v
        return dict(agg)

    def available_resources(self) -> Dict[str, float]:
        agg: Dict[str, float] = defaultdict(float)
        for m in self.state.available_map().values():
            for k, v in m.items():
                agg[k] += v
        return dict(agg)

    def nodes(self) -> List[dict]:
        return [
            {
                "NodeID": nid,
                "Alive": bool(self.state.alive[i]),
                "Resources": self.space.unvector(self.state.total[i]),
            }
            for i, nid in enumerate(self.state.node_ids)
        ]

    def timeline(self) -> List[dict]:
        # full history from the spill stream (the in-memory window alone
        # would truncate long runs' timelines)
        return list(self._task_events.scan())

    # -------------------------------------------------- state API (local)
    # reference: python/ray/util/state served from GCS task events

    def list_tasks(self, limit: int = 1000) -> List[dict]:
        return self._task_events.tail(limit)

    def summarize_tasks(self) -> dict:
        total, by_name = self._task_events.stats()
        return {"total": total, "by_name": by_name}

    def list_actors(self) -> List[dict]:
        out = []
        with self._lock:
            for aid, st in self._actors.items():
                out.append({
                    "actor_id": aid,
                    "state": "DEAD" if st.dead else "ALIVE",
                    "node_id": self.node_id,
                    "class_name": type(st.instance).__name__ if st.instance else "",
                    "name": "",
                })
        return out

    def list_placement_groups(self) -> List[dict]:
        with self._lock:
            return [
                {"placement_group_id": pid, **{k: v for k, v in pg.items()
                                               if k in ("state", "strategy", "bundles")}}
                for pid, pg in self._pgs.items()
            ]

    def list_objects(self, limit: int = 1000) -> List[dict]:
        return self.store.list_entries(limit)

    def summary(self) -> dict:
        with self._lock:
            return {
                "nodes_alive": 1,
                "nodes_dead": 0,
                "tasks_pending": len(self._pending) + len(self._waiting),
                "tasks_running": len(self._running),
                "actors": len(self._actors),
                "placement_groups": len(self._pgs),
            }

    def current_task_id(self) -> Optional[str]:
        spec = getattr(_context, "task", None)
        return spec.task_id if spec else None

    def current_actor_id(self) -> Optional[str]:
        return getattr(_context, "actor_id", None)

    def shutdown(self):
        self._stopped = True
        self._task_events.close()
        self._kick()
        for st in list(self._actors.values()):
            with st.cv:
                st.dead = True
                st.cv.notify()
        self._executor.shutdown(wait=False, cancel_futures=True)
        if self._sched_thread.is_alive():
            self._sched_thread.join(timeout=2)
