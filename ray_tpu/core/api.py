"""Public API: init/get/put/wait + the @remote machinery.

Reference surfaces:
- init/get/put/wait: python/ray/_private/worker.py (init, get, put, wait)
- @remote for functions: python/ray/remote_function.py (RemoteFunction._remote)
- @remote for classes: python/ray/actor.py (ActorClass._remote, ActorHandle)
- option validation: python/ray/_private/ray_option_utils.py
"""

from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core.config import set_global_config
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec, new_id

_global_lock = threading.Lock()
_runtime = None
_embedded_cluster = None


def init(
    address: Optional[str] = None,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
    cluster: bool = False,
    num_nodes: int = 1,
    resources_per_node: Optional[Dict[str, float]] = None,
    config: Optional[Dict[str, Any]] = None,
    **kwargs,
):
    """Start (or connect to) the runtime.

    address=None -> local mode (one in-process node, reference local Ray);
    address="tcp://host:port" -> connect to a running cluster's control
    service (multi-node mode, ray_tpu.cluster);
    cluster=True -> boot an EMBEDDED cluster (in-process GCS + num_nodes
    daemons with resources_per_node, workers as real subprocesses) and
    connect to it; shutdown() tears it down. The multi-process topology
    without managing Cluster() by hand — e.g. what torch.distributed
    worker groups need (local-mode actors are threads of one process).
    """
    global _runtime, _embedded_cluster
    if kwargs:
        # silently swallowing typos/unsupported options sent callers to
        # local mode while they believed a flag took effect
        raise TypeError(f"init() got unexpected arguments: {sorted(kwargs)}")
    with _global_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                if cluster and _embedded_cluster is None:
                    # returning the existing (possibly local-mode) runtime
                    # would be the believed-a-flag-took-effect trap the
                    # strict-kwargs check above exists to prevent
                    raise RuntimeError(
                        "init(cluster=True, ignore_reinit_error=True): the "
                        "runtime is already initialized WITHOUT an embedded "
                        "cluster; shutdown() first"
                    )
                return _runtime
            raise RuntimeError("ray_tpu.init() called twice; use shutdown() first")
        if config and _system_config:
            raise TypeError("pass config= or _system_config=, not both")
        config_dict = config or _system_config
        if cluster:
            if address is not None:
                raise TypeError("cluster=True boots its own cluster; "
                                "drop address= or drop cluster=True")
            from ray_tpu.core.config import Config
            from ray_tpu.cluster.cluster_utils import Cluster

            per_node = dict(resources_per_node or {})
            # num_cpus/num_tpus/resources apply PER NODE here — silently
            # dropping them would hang tasks that demand those resources
            per_node.setdefault("CPU", float(num_cpus or 4))
            if num_tpus is not None:
                per_node.setdefault("TPU", float(num_tpus))
            for k, v in (resources or {}).items():
                per_node.setdefault(k, float(v))
            n = max(int(num_nodes), 1)
            c = Cluster(config=Config(config_dict or {}))
            try:
                for _ in range(n):
                    c.add_node(
                        num_cpus=per_node["CPU"],
                        resources={k: v for k, v in per_node.items()
                                   if k != "CPU"},
                    )
                c.wait_for_nodes(n)
            except BaseException:
                c.shutdown()  # never leak GCS/daemon subprocesses
                raise
            _embedded_cluster = c
            address = c.address
        try:
            config = set_global_config(config_dict)
            res = dict(resources or {})
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            if address is None:
                # worker processes inherit the cluster address (reference:
                # RAY_ADDRESS / ray.init auto-connect inside workers)
                import os as _os

                address = _os.environ.get("RAY_TPU_GCS_ADDR") or None
            if address is None:
                from ray_tpu.core.runtime import LocalRuntime

                _runtime = LocalRuntime(
                    num_cpus=num_cpus, resources=res, config=config
                )
            else:
                try:
                    from ray_tpu.cluster.client import ClusterClient
                except ImportError as e:
                    raise RuntimeError(
                        "cluster mode (init(address=...)) is not available "
                        "in this build"
                    ) from e
                _runtime = ClusterClient(address, config=config)
        except BaseException:
            # a failure past the embedded-cluster boot must not strand its
            # GCS/daemon/worker subprocesses (a retry would rebind
            # _embedded_cluster and leak them permanently)
            _runtime = None
            if _embedded_cluster is not None:
                try:
                    _embedded_cluster.shutdown()
                finally:
                    _embedded_cluster = None
            raise
        # opt-in tracing (reference: RAY_TRACING_ENABLED installing the
        # span wrappers at init)
        from ray_tpu.util import tracing as _tracing

        if _tracing.tracing_enabled():
            _tracing.enable_task_spans()
        return _runtime


def shutdown():
    global _runtime, _embedded_cluster
    with _global_lock:
        try:
            if _runtime is not None:
                _runtime.shutdown()
        finally:
            _runtime = None
            if _embedded_cluster is not None:
                try:
                    _embedded_cluster.shutdown()
                finally:
                    _embedded_cluster = None


def is_initialized() -> bool:
    return _runtime is not None


def _get_runtime():
    if _runtime is None:
        # Auto-init only from the main thread (reference: implicit ray.init
        # on first use). Background/daemon threads must never resurrect a
        # runtime after shutdown — a stray actor-side thread doing so leaks
        # a whole new runtime between tests/apps.
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "ray_tpu is not initialized (and auto-init is main-thread "
                "only); call ray_tpu.init() first"
            )
        init()
    return _runtime


def _set_runtime_for_worker(rt):
    """Internal: cluster worker processes install their runtime here."""
    global _runtime
    _runtime = rt


# --------------------------------------------------------------------- options

_VALID_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "num_returns",
    "max_retries", "max_restarts", "max_concurrency", "name",
    "scheduling_strategy", "memory", "runtime_env", "lifetime",
    # streaming generators: bound on unacked in-flight yielded objects
    # (reference: _raylet.pyx _generator_backpressure_num_objects)
    "_backpressure_num_objects",
}


def _resources_from_options(opts: Dict[str, Any], default_cpus: float) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    res["CPU"] = float(opts.get("num_cpus", default_cpus))
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v}


def _strategy_from_options(opts: Dict[str, Any]) -> SchedulingStrategy:
    s = opts.get("scheduling_strategy")
    if s is None or s == "DEFAULT":
        return SchedulingStrategy()
    if s == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if isinstance(s, SchedulingStrategy):
        return s
    # PlacementGroupSchedulingStrategy / NodeAffinitySchedulingStrategy objects
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(s, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(kind="NODE_AFFINITY", node_id=s.node_id, soft=s.soft)
    if isinstance(s, NodeLabelSchedulingStrategy):
        def _norm(d):
            # accept "value" or ["v1", "v2"] per key
            return {
                k: list(v) if isinstance(v, (list, tuple, set)) else [v]
                for k, v in (d or {}).items()
            }

        return SchedulingStrategy(
            kind="NODE_LABEL",
            labels_hard=_norm(s.hard),
            labels_soft=_norm(s.soft),
        )
    if isinstance(s, PlacementGroupSchedulingStrategy):
        pg = s.placement_group
        pg_id = pg.id if hasattr(pg, "id") else str(pg)
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=pg_id,
            bundle_index=s.placement_group_bundle_index,
        )
    raise ValueError(f"unsupported scheduling_strategy: {s!r}")


def _check_options(opts: Dict[str, Any]):
    bad = set(opts) - _VALID_OPTIONS
    if bad:
        raise ValueError(f"invalid @remote options: {sorted(bad)}")
    # validate eagerly: a typo'd runtime_env key must fail at definition
    # time, never silently no-op (reference: runtime env validation in
    # python/ray/_private/ray_option_utils.py)
    from ray_tpu.core import runtime_env as _rtenv

    _rtenv.validate(opts.get("runtime_env"))


# ------------------------------------------------------------ remote functions

class RemoteFunction:
    """Handle produced by @remote on a function (reference:
    python/ray/remote_function.py)."""

    def __init__(self, func, options: Dict[str, Any]):
        _check_options(options)
        self._func = func
        self._options = options
        functools.update_wrapper(self, func)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(opts)
        return RemoteFunction(self._func, merged)

    def remote(self, *args, **kwargs):
        rt = _get_runtime()
        opts = self._options
        nr = opts.get("num_returns", 1)
        # streaming generator returns (reference: _raylet.pyx
        # num_returns="streaming"): the caller gets an ObjectRefGenerator
        # yielding refs as the task produces them; the declared return
        # slot carries the end-of-stream marker (see core/generator.py)
        streaming = nr == "streaming"
        num_returns = 1 if streaming else int(nr)
        max_retries = int(opts.get("max_retries", rt.config.task_max_retries))
        spec = TaskSpec(
            task_id=new_id("task"),
            func=self._func,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources=_resources_from_options(opts, default_cpus=1.0),
            max_retries=max_retries,
            retries_left=max_retries,
            strategy=_strategy_from_options(opts),
            owner_id=rt.worker_id,
            name=opts.get("name") or getattr(self._func, "__name__", "task"),
            runtime_env=opts.get("runtime_env"),
            streaming=streaming,
            backpressure=int(opts.get("_backpressure_num_objects", 0)),
        )
        refs = rt.submit_task(spec)
        if streaming:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(
                spec.task_id, rt.worker_id, ack=spec.backpressure > 0
            )
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Lazy DAG construction (reference: ray.dag — fn.bind): returns a
        FunctionNode instead of submitting; DAGNode arguments become graph
        edges. ``node.execute(x)`` eager-interprets via .remote();
        ``node.compile()`` builds a pinned-worker pipeline (ray_tpu.dag)."""
        from ray_tpu.dag.api import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly; use .remote()."
        )


# --------------------------------------------------------------------- actors

class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, **opts):
        nr = opts.get("num_returns", self._num_returns)
        m = ActorMethod(self._handle, self._method_name,
                        nr if nr == "streaming" else int(nr))
        return m

    def remote(self, *args, **kwargs):
        return self._handle._invoke(
            self._method_name, args, kwargs, self._num_returns
        )

    def bind(self, *args, **kwargs):
        """Lazy DAG construction (reference: ray.dag — actor.method.bind):
        the resulting stage stays pinned to the worker hosting this actor
        when the graph is compiled (ray_tpu.dag)."""
        from ray_tpu.dag.api import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    """Reference to a live actor (reference: python/ray/actor.py ActorHandle).
    Picklable: other tasks can call through it."""

    def __init__(self, actor_id: str, method_meta: Dict[str, int], creation_ref: ObjectRef,
                 name: str = ""):
        self._actor_id = actor_id
        self._method_meta = method_meta
        self._creation_ref = creation_ref
        self._name = name

    def _invoke(self, method_name: str, args, kwargs, num_returns):
        rt = _get_runtime()
        streaming = num_returns == "streaming"
        nr = 1 if streaming else int(num_returns)
        spec = TaskSpec(
            task_id=new_id("atask"),
            func=None,
            args=args,
            kwargs=kwargs,
            num_returns=nr,
            resources={},
            max_retries=0,
            retries_left=0,
            actor_id=self._actor_id,
            method_name=method_name,
            owner_id=rt.worker_id,
            name=f"{self._actor_id[:12]}.{method_name}",
            streaming=streaming,
        )
        refs = rt.submit_task(spec)
        if streaming:
            from ray_tpu.core.generator import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, rt.worker_id)
        return refs[0] if nr == 1 else refs

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_meta:
            raise AttributeError(f"actor has no method {name!r}")
        return ActorMethod(self, name, self._method_meta[name])

    def __reduce__(self):
        return (_rebuild_actor_handle,
                (self._actor_id, self._method_meta, self._creation_ref,
                 self._name))

    def __repr__(self):
        return f"ActorHandle({self._actor_id})"


def _rebuild_actor_handle(actor_id, method_meta, creation_ref, name=""):
    return ActorHandle(actor_id, method_meta, creation_ref, name)


class ActorClass:
    """Produced by @remote on a class (reference: python/ray/actor.py)."""

    def __init__(self, cls, options: Dict[str, Any]):
        _check_options(options)
        self._cls = cls
        self._options = options

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = _get_runtime()
        opts = self._options
        actor_id = new_id("actor")
        # Async actors (reference: python/ray/actor.py — a class with any
        # coroutine method runs its tasks on a per-actor asyncio event
        # loop). Detection happens here so the default concurrency matches
        # upstream: async actors admit many in-flight coroutines unless
        # the user caps them explicitly.
        from ray_tpu.core.async_actor import class_is_async

        default_mc = 1000 if class_is_async(self._cls) else 1
        spec = TaskSpec(
            task_id=new_id("acreate"),
            func=self._cls,
            args=args,
            kwargs=kwargs,
            num_returns=1,
            resources=_resources_from_options(opts, default_cpus=1.0),
            max_retries=0,
            retries_left=0,
            strategy=_strategy_from_options(opts),
            actor_id=actor_id,
            actor_creation=True,
            max_restarts=int(opts.get("max_restarts", 0)),
            max_concurrency=int(opts.get("max_concurrency", default_mc)),
            owner_id=rt.worker_id,
            name=opts.get("name") or f"{self._cls.__name__}.__init__",
            runtime_env=opts.get("runtime_env"),
        )
        refs = rt.submit_task(spec)
        method_meta = {}
        for mname, m in inspect.getmembers(self._cls, inspect.isfunction):
            if not mname.startswith("_"):
                method_meta[mname] = int(getattr(m, "__num_returns__", 1))
        handle = ActorHandle(actor_id, method_meta, refs[0],
                             name=opts.get("name") or "")
        if opts.get("name"):
            # named-actor registry via the internal KV (reference:
            # gcs_actor_manager named actors + ray.get_actor); last
            # registration wins
            import pickle as _pickle

            rt.kv_put(f"named_actor:{opts['name']}", _pickle.dumps(handle))
        return handle

    def __call__(self, *a, **kw):
        raise TypeError("Actor classes cannot be instantiated directly; use .remote().")


def method(*, num_returns: int = 1):
    """Per-method options decorator (reference: ray.method)."""

    def deco(f):
        f.__num_returns__ = num_returns
        return f

    return deco


def remote(*args, **options):
    """@remote / @remote(num_cpus=...) on functions and classes."""
    if len(args) == 1 and callable(args[0]) and not options:
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target, {})
    if args:
        raise TypeError("use @remote or @remote(**options)")

    def deco(target):
        if inspect.isclass(target):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    return deco


# ----------------------------------------------------------------- data plane

def put(value: Any) -> ObjectRef:
    return _get_runtime().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    rt = _get_runtime()
    single = isinstance(refs, ObjectRef)
    if not single and not hasattr(refs, "__iter__"):
        raise TypeError(
            f"get() expects an ObjectRef or a list of ObjectRefs, got {type(refs)}"
        )
    lst = [refs] if single else list(refs)
    for r in lst:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    vals = rt.get(lst, timeout=timeout)
    return vals[0] if single else vals


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns={num_returns} exceeds the number of refs ({len(refs)})"
        )
    return _get_runtime().wait(refs, num_returns=num_returns, timeout=timeout)


def cancel(ref: ObjectRef, *, force: bool = False):
    rt = _get_runtime()
    if hasattr(rt, "cancel"):
        rt.cancel(ref, force=force)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    rt = _get_runtime()
    rt.kill_actor(actor._actor_id, no_restart=no_restart)
    # drop the named-actor registration so get_actor stops returning a
    # handle to a dead actor (reference: named actor entry removed on death)
    # — but only if the registry still points at THIS actor (a newer actor
    # may have reused the name; last-registration-wins must survive the kill
    # of its predecessor)
    if getattr(actor, "_name", ""):
        import pickle as _pickle

        key = f"named_actor:{actor._name}"
        try:
            data = rt.kv_get(key)
            if data is not None and _pickle.loads(data)._actor_id == actor._actor_id:
                rt.kv_del(key)
        except Exception:
            pass


# ------------------------------------------------------------------- metadata

class RuntimeContext:
    def __init__(self, rt):
        self._rt = rt

    @property
    def node_id(self):
        return self._rt.node_id

    def get_task_id(self):
        return self._rt.current_task_id()

    def get_actor_id(self):
        return self._rt.current_actor_id()

    def get_node_id(self):
        return self._rt.node_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_get_runtime())


def get_actor(name: str) -> ActorHandle:
    """Look up a live named actor (reference: ray.get_actor)."""
    import pickle as _pickle

    data = _get_runtime().kv_get(f"named_actor:{name}")
    if data is None:
        raise ValueError(f"no actor registered with name {name!r}")
    return _pickle.loads(data)


def nodes() -> List[dict]:
    return _get_runtime().nodes()


def cluster_resources() -> Dict[str, float]:
    return _get_runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _get_runtime().available_resources()


def timeline() -> List[dict]:
    """Task-event timeline (reference: `ray timeline` Chrome-trace export)."""
    return _get_runtime().timeline()
