"""ObjectRefGenerator: streaming generator task returns.

Reference: python/ray/_raylet.pyx (ObjectRefGenerator /
num_returns="streaming") — a generator task yields ObjectRefs to its
caller INCREMENTALLY, as the remote generator produces them, instead of
materializing every return before the task completes. Upstream Ray Data's
streaming executor is built on this; here ``ray_tpu.data``'s map exchange
adopts it the same way.

Wire protocol (shared by local and cluster mode):
  - output index 0 is the END MARKER — the task's one declared return.
    On success it holds the item count; on failure it holds the error.
    Because it IS the normal task result, every existing completion path
    (task_result pushes, retries, worker-death errors, lineage) applies
    to stream termination unchanged.
  - yielded item i (0-based) lands at output index i+1, published as the
    task produces it.

Semantics:
  - iteration blocks until the next item exists (or the stream ends);
  - a mid-stream failure delivers the error as the LAST element — the
    ref is yielded and raising happens at ``get`` (upstream behavior);
  - each ``__next__`` acks the consumed index, releasing the producer's
    backpressure window (``_backpressure_num_objects``);
  - a retried streaming task re-runs the whole generator (at-least-once,
    as upstream); already-consumed refs stay valid.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu.core.object_ref import ObjectRef


def end_marker_ref(task_id: str, owner: Optional[str] = None) -> ObjectRef:
    return ObjectRef.for_task_output(task_id, 0, owner=owner)


def item_ref(task_id: str, i: int, owner: Optional[str] = None) -> ObjectRef:
    """Ref for 0-based yielded item i (wire index i+1)."""
    return ObjectRef.for_task_output(task_id, i + 1, owner=owner)


class ObjectRefGenerator:
    """Iterator of ObjectRefs for one streaming task's yields."""

    def __init__(self, task_id: str, owner_id: Optional[str],
                 ack: bool = False):
        self._task_id = task_id
        self._owner = owner_id
        # acks exist only to widen the producer's backpressure window;
        # skip the per-item runtime call when no window was requested
        self._ack = ack
        self._i = 0  # next 0-based item index to hand out
        self._count: Optional[int] = None  # known once the end marker lands
        self._error_delivered = False

    @property
    def task_id(self) -> str:
        return self._task_id

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._next(timeout=None)

    def next_ready(self, timeout: float) -> ObjectRef:
        """Like __next__ but raises TimeoutError if no item arrives in
        ``timeout`` seconds (StopIteration still signals exhaustion)."""
        return self._next(timeout=timeout)

    def _next(self, timeout: Optional[float]) -> ObjectRef:
        from ray_tpu.core.api import _get_runtime

        rt = _get_runtime()
        deadline = None if timeout is None else time.time() + timeout
        end = end_marker_ref(self._task_id, self._owner)
        while True:
            if self._count is not None and self._i >= self._count:
                raise StopIteration
            item = item_ref(self._task_id, self._i, self._owner)
            if rt.stream_item_ready(item):
                self._i += 1
                if self._ack:
                    rt.stream_ack(self._task_id, self._i)
                return item
            if self._count is not None:
                # The end marker proves this item was produced (it landed
                # before the count). A lost push (daemon->GCS relay
                # failure, driver reconnect) must not spin or hang the
                # consumer: hand the ref out with a pull-through hint so
                # get() fetches it via the GCS directory.
                mark = getattr(rt, "stream_mark_remote", None)
                if mark is not None:
                    mark(item)
                self._i += 1
                if self._ack:
                    rt.stream_ack(self._task_id, self._i)
                return item
            if self._count is None and rt.stream_item_ready(end):
                value, is_err = rt.stream_read_end(end)
                if is_err:
                    # The error marker carries no produced-count, so check
                    # whether THIS item was actually produced before the
                    # failure (its push announcement may have been lost on
                    # a reconnect) — produced items are never dropped.
                    locate = getattr(rt, "stream_locate", None)
                    if locate is not None and locate(item):
                        mark = getattr(rt, "stream_mark_remote", None)
                        if mark is not None:
                            mark(item)
                        continue  # now ready; delivered by the re-check
                    # the failure is the stream's last element: hand out
                    # the marker ref (get() raises the task error), then
                    # stop. Items published before the failure were
                    # already consumable.
                    if self._error_delivered:
                        raise StopIteration
                    self._error_delivered = True
                    self._count = self._i
                    return end
                self._count = int(value)
                continue  # re-check: the item may exist after all
            if deadline is not None and time.time() >= deadline:
                raise TimeoutError(
                    f"no stream item from {self._task_id} within {timeout}s"
                )
            remaining = 1.0 if deadline is None else min(
                1.0, max(0.05, deadline - time.time())
            )
            rt.stream_wait_any([item, end], timeout=remaining)

    def completed(self) -> bool:
        """True once every yielded item has been handed out."""
        return self._count is not None and self._i >= self._count

    @property
    def errored(self) -> bool:
        """True if the stream terminated with an error (the last handed-out
        ref raises it on get)."""
        return self._error_delivered

    def __del__(self):
        # Abandoned consumer: a backpressured producer would otherwise
        # park on acks that never come, wedging its worker forever. A
        # final unbounded ack lets it run to completion (items land in
        # the store unconsumed; normal eviction reclaims them).
        if self._ack and not self.completed():
            try:
                from ray_tpu.core import api as _api

                # only an ALREADY-LIVE runtime: _get_runtime() would
                # auto-init a fresh one if GC runs after shutdown()
                rt = _api._runtime
                if rt is not None:
                    rt.stream_ack(self._task_id, 1 << 30)
            except Exception:  # noqa: BLE001 - interpreter teardown etc.
                pass

    def __reduce__(self):
        # Streams are push-delivered to the OWNER's connection only; a
        # pickled generator on another worker would wait on pushes that
        # never arrive there. Hand out the ObjectRefs instead (they are
        # location-addressed and travel fine).
        raise TypeError(
            "ObjectRefGenerator is not serializable: consume it where the "
            "task was submitted and pass the yielded ObjectRefs instead"
        )
