"""Async (asyncio) actor support: one event loop per actor.

Reference: python/ray/actor.py + src/ray/core_worker async actor support —
an actor class with any coroutine method runs its tasks on a dedicated
per-actor asyncio event loop; ``max_concurrency`` bounds the number of
in-flight coroutines. Coroutines from different calls interleave at await
points on ONE loop thread, so asyncio primitives (Event, Lock, Condition)
coordinate naturally across calls — the capability Serve's handle
composition and the distributed Queue lean on.

Execution model here: dispatch threads (the actor's concurrency slots)
resolve args and report results — blocking RPC work that must not stall
the loop — and bridge into the loop only for the user method itself via
``ActorEventLoop.call``. Sync methods of an async actor also run ON the
loop (matching upstream: everything the user wrote executes on the loop
thread, so actor state is never touched from two OS threads at once).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Callable


def class_is_async(cls) -> bool:
    """Upstream detection rule: any coroutine (or async generator) method
    makes it an async actor (python/ray/actor.py _is_asyncio)."""
    return any(
        inspect.iscoroutinefunction(m) or inspect.isasyncgenfunction(m)
        for _, m in inspect.getmembers(cls, inspect.isfunction)
    )


def agen_to_iter(agen, aio: "ActorEventLoop"):
    """Bridge an async-generator actor method into a plain iterator:
    each item is pulled by running __anext__ on the actor's event loop
    (streamed async-gen methods, reference: _raylet.pyx async streaming
    generators)."""
    while True:
        try:
            yield aio.call(agen.__anext__, (), {})
        except StopAsyncIteration:
            return


class ActorEventLoop:
    """A per-actor asyncio loop on a dedicated daemon thread, with a
    blocking bridge for the actor's dispatch threads."""

    #: bound on the post-stop drain: a coroutine that catches
    #: CancelledError and keeps awaiting must not wedge the loop thread
    #: (and with it every dispatch thread blocked in call()) forever
    DRAIN_TIMEOUT_S = 5.0

    def __init__(self, name: str):
        self.loop = asyncio.new_event_loop()
        self._closed = False
        # wall-clock bound past which call() treats the actor as dead
        # even though the loop thread is still alive (a stubborn
        # coroutine riding out the drain window); set by shutdown()
        self._dead_at = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()
        # Drain before close. Two distinct leftovers exist after stop():
        # 1) tasks that survived cancellation (caught CancelledError and
        #    kept awaiting) — wait for them, BOUNDED: asyncio.wait with a
        #    timeout (NOT wait_for/gather-cancel, which would block until
        #    the stubborn task acknowledges a cancellation it swallows);
        # 2) done-callbacks of tasks that were cancelled DURING shutdown:
        #    a task's done-callback (which resolves the caller's bridge
        #    future in run_coroutine_threadsafe's chaining) is call_soon-
        #    scheduled AFTER the already-queued loop.stop, so it has not
        #    run yet — closing now would strand every blocked call() in
        #    fut.result() forever. One sleep(0) cycle flushes them.
        try:
            pending = asyncio.all_tasks(self.loop)
            if pending:
                self.loop.run_until_complete(
                    asyncio.wait(pending, timeout=self.DRAIN_TIMEOUT_S)
                )
            self.loop.run_until_complete(asyncio.sleep(0))
        finally:
            try:
                self.loop.close()
            except RuntimeError:
                pass  # a still-pending stubborn task; the thread exits

    def call(self, method: Callable, args: tuple, kwargs: dict) -> Any:
        """Run a user method on the loop from a dispatch thread, blocking
        until it completes. Coroutine methods are awaited; sync methods
        run inline on the loop thread (briefly blocking other coroutines,
        as upstream does)."""
        if self._closed:
            raise RuntimeError("actor event loop is shut down")

        async def _invoke():
            r = method(*args, **kwargs)
            # isawaitable, not iscoroutine: __anext__ of an async
            # generator returns an async_generator_asend object, which
            # must be awaited too (streamed async-gen methods)
            if inspect.isawaitable(r):
                return await r
            return r

        fut = asyncio.run_coroutine_threadsafe(_invoke(), self.loop)
        # Not a bare fut.result(): a call racing shutdown() can slip its
        # bridge callback into the loop's queue after the drain's last
        # cycle — loop.close() then discards it and the future never
        # resolves. Poll with a bound so the dispatch thread surfaces the
        # actor's death instead of wedging forever.
        import concurrent.futures as _cf

        import time as _time

        while True:
            try:
                return fut.result(timeout=0.5)
            except _cf.TimeoutError:
                # (closed + thread dead) OR (closed + the shutdown grace
                # window expired): either way the loop will never resolve
                # this bridge future — a stubborn coroutine that swallows
                # CancelledError keeps the THREAD alive, so thread death
                # alone is not a sufficient wedge signal
                if self._closed and (
                    not self._thread.is_alive()
                    or (self._dead_at is not None
                        and _time.time() > self._dead_at)
                ):
                    if not self.loop.is_closed():
                        # cancelling after close would fire the bridge
                        # future's cross-loop callback into a closed
                        # loop (logged noise, no effect)
                        fut.cancel()
                    raise RuntimeError(
                        "actor event loop shut down during call"
                    ) from None

    def shutdown(self, join_timeout: float = 2.0):
        """Cancel every in-flight coroutine and stop the loop. Dispatch
        threads blocked in call() observe CancelledError on their bridge
        futures — the actor's death propagates to callers as task
        errors."""
        if self._closed:
            return
        self._closed = True
        import time as _time

        # past this point call() gives up on unresolved bridge futures
        # even if the loop thread is still draining a stubborn coroutine
        self._dead_at = _time.time() + join_timeout + self.DRAIN_TIMEOUT_S

        def _cancel_and_stop():
            for t in asyncio.all_tasks(self.loop):
                t.cancel()
            # cancellation resumptions were scheduled first; stop after
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_cancel_and_stop)
        except RuntimeError:
            return  # loop already closed
        self._thread.join(timeout=join_timeout)
