"""Runtime environments v1: env_vars + working_dir.

Reference: python/ray/_private/runtime_env/ (working_dir.py uploads the
directory to GCS storage once, content-addressed; workers download and
extract it into the session dir and chdir; env_vars merge into the worker
environment). Same shape here: the driver zips working_dir into the GCS KV
under a content hash, workers extract it to a per-hash cache dir and run the
task inside it.

Unknown keys raise loudly — the silently-ignored `runtime_env` option was a
round-2/3 verdict correctness trap.

Local-mode caveat: LocalRuntime executes tasks on threads in one process, so
env_vars/cwd are applied process-globally under a lock for the task's
duration; concurrently running tasks without a runtime_env may observe them.
Cluster mode applies them in the (per-task / per-actor) worker process.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import threading
import zipfile
from typing import Any, Dict, Optional

_SUPPORTED_KEYS = {"env_vars", "working_dir"}
MAX_WORKING_DIR_BYTES = 256 * 1024 * 1024
KV_PREFIX = "rtenv:wd:"

# process-global: env/cwd mutation is process-wide state
_apply_lock = threading.Lock()


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate at task-definition time; raises on anything unsupported so a
    typo'd or unimplemented key never silently no-ops."""
    if runtime_env is None:
        return None
    if not isinstance(runtime_env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(runtime_env)}")
    unknown = set(runtime_env) - _SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED_KEYS)}"
        )
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()
        ):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise TypeError("runtime_env['working_dir'] must be a path string")
        if not os.path.isdir(wd):
            raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
    return dict(runtime_env)


def package_working_dir(path: str) -> tuple:
    """Zip a directory into bytes. The key hashes (relpath, file contents)
    in sorted traversal order with fixed zip timestamps, so identical trees
    always produce identical keys regardless of mtimes or os.walk order
    (reference: working_dir_upload content hashing)."""
    buf = io.BytesIO()
    digest = hashlib.sha1()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs.sort()  # deterministic traversal
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                with open(full, "rb") as f:
                    content = f.read()
                total += len(content)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"working_dir {path!r} exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20}MB"
                    )
                digest.update(rel.encode())
                digest.update(b"\0")
                digest.update(content)
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                zf.writestr(info, content)
    return KV_PREFIX + digest.hexdigest(), buf.getvalue()


def ensure_working_dir(key: str, data: bytes, root: str) -> str:
    """Extract (once, cached by hash) and return the directory path.
    Concurrency-safe: extraction goes to a private temp dir that is
    atomically renamed into place; a loser of the rename race uses the
    winner's copy."""
    dest = os.path.join(root, "runtime_envs", key.split(":")[-1])
    if os.path.isdir(dest):
        return dest
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f"{dest}.tmp.{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        # another process won the race; its fully-extracted copy serves
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


@contextlib.contextmanager
def applied(env_vars: Optional[Dict[str, str]] = None,
            cwd: Optional[str] = None, keep: bool = False):
    """Apply env_vars/cwd process-wide for the task's duration. keep=True
    (actor creation) leaves them in place — the dedicated actor worker owns
    its environment for the actor's lifetime."""
    if not env_vars and not cwd:
        yield
        return
    _apply_lock.acquire()
    saved_env = {k: os.environ.get(k) for k in (env_vars or {})}
    saved_cwd = os.getcwd() if cwd else None
    try:
        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        if cwd:
            os.chdir(cwd)
        yield
    finally:
        if keep:
            _apply_lock.release()
        else:
            try:
                for k, old in saved_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                if saved_cwd:
                    os.chdir(saved_cwd)
            finally:
                _apply_lock.release()
