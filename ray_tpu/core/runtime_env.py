"""Runtime environments: env_vars + working_dir + py_modules + local pip.

Reference: python/ray/_private/runtime_env/ (working_dir.py uploads the
directory to GCS storage once, content-addressed; workers download and
extract it into the session dir and chdir; py_modules.py ships local
module trees the same way and prepends them to sys.path; pip.py builds a
per-env package dir; env_vars merge into the worker environment). Same
shape here: the driver zips working_dir / each py_module into the GCS KV
under a content hash, workers extract to a per-hash cache dir; `pip`
installs from a LOCAL wheels directory (--no-index --find-links — this
environment has zero egress, so PyPI pip/conda stay out of scope) into a
per-spec target dir prepended to sys.path.

Unknown keys raise loudly — the silently-ignored `runtime_env` option was a
round-2/3 verdict correctness trap.

Local-mode caveat: LocalRuntime executes tasks on threads in one process, so
env_vars/cwd/sys.path are applied process-globally under a lock for the
task's duration; concurrently running tasks without a runtime_env may
observe them. Cluster mode applies them in the (per-task / per-actor)
worker process.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional

_SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules", "pip"}
MAX_WORKING_DIR_BYTES = 256 * 1024 * 1024
KV_PREFIX = "rtenv:wd:"
PYMOD_KV_PREFIX = "rtenv:pymod:"

# process-global: env/cwd mutation is process-wide state
_apply_lock = threading.Lock()


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Validate at task-definition time; raises on anything unsupported so a
    typo'd or unimplemented key never silently no-ops."""
    if runtime_env is None:
        return None
    if not isinstance(runtime_env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(runtime_env)}")
    unknown = set(runtime_env) - _SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED_KEYS)}"
        )
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()
        ):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise TypeError("runtime_env['working_dir'] must be a path string")
        if not os.path.isdir(wd):
            raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
    mods = runtime_env.get("py_modules")
    if mods is not None:
        if not isinstance(mods, (list, tuple)):
            raise TypeError(
                "runtime_env['py_modules'] must be a list of paths"
            )
        for m in mods:
            if not isinstance(m, str):
                raise TypeError(f"py_modules entry {m!r} must be a path string")
            if not (
                os.path.isdir(m)
                or (os.path.isfile(m) and m.endswith(".py"))
            ):
                raise ValueError(
                    f"py_modules entry {m!r} must be a package directory "
                    "or a .py file"
                )
    pip = runtime_env.get("pip")
    if pip is not None:
        if (
            not isinstance(pip, dict)
            or not isinstance(pip.get("packages"), (list, tuple))
            or not isinstance(pip.get("wheels_dir"), str)
        ):
            raise TypeError(
                "runtime_env['pip'] must be {'packages': [...], "
                "'wheels_dir': <local dir>} — zero-egress environments "
                "install from a local wheels directory, not PyPI"
            )
        if not os.path.isdir(pip["wheels_dir"]):
            raise ValueError(
                f"pip wheels_dir {pip['wheels_dir']!r} is not a directory"
            )
    return dict(runtime_env)


def package_working_dir(path: str) -> tuple:
    """Zip a directory into bytes. The key hashes (relpath, file contents)
    in sorted traversal order with fixed zip timestamps, so identical trees
    always produce identical keys regardless of mtimes or os.walk order
    (reference: working_dir_upload content hashing)."""
    buf = io.BytesIO()
    digest = hashlib.sha1()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs.sort()  # deterministic traversal
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                with open(full, "rb") as f:
                    content = f.read()
                total += len(content)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"working_dir {path!r} exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20}MB"
                    )
                digest.update(rel.encode())
                digest.update(b"\0")
                digest.update(content)
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                zf.writestr(info, content)
    return KV_PREFIX + digest.hexdigest(), buf.getvalue()


def package_py_module(path: str) -> tuple:
    """Zip one py_module (package dir or single .py file) into bytes,
    content-addressed like working_dir. Entries are prefixed with the
    module's import name, so the EXTRACTION DIRECTORY itself is the
    sys.path root (reference: py_modules.py upload_py_modules_if_needed)."""
    path = path.rstrip("/")
    buf = io.BytesIO()
    digest = hashlib.sha1()
    if os.path.isfile(path):
        name = os.path.basename(path)
        with open(path, "rb") as f:
            content = f.read()
        digest.update(name.encode())
        digest.update(b"\0")
        digest.update(content)
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            zf.writestr(info, content)
        return PYMOD_KV_PREFIX + digest.hexdigest(), buf.getvalue()
    base = os.path.basename(path)
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs.sort()
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.join(base, os.path.relpath(full, path))
                with open(full, "rb") as f:
                    content = f.read()
                total += len(content)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"py_module {path!r} exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20}MB"
                    )
                digest.update(rel.encode())
                digest.update(b"\0")
                digest.update(content)
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                zf.writestr(info, content)
    return PYMOD_KV_PREFIX + digest.hexdigest(), buf.getvalue()


def ensure_pip_env(pip_spec: Dict[str, Any], root: str) -> str:
    """Install the requested packages from a LOCAL wheels directory into a
    per-spec target dir (once, cached by spec hash) and return it for
    sys.path. ``pip install --no-index --find-links`` keeps this fully
    offline (reference: pip.py's per-runtime-env virtualenv; a --target
    dir gives the same isolation for pure-Python deps without venv cost)."""
    import subprocess

    spec_key = hashlib.sha1(
        repr((sorted(pip_spec["packages"]),
              os.path.realpath(pip_spec["wheels_dir"]))).encode()
    ).hexdigest()
    dest = os.path.join(root, "runtime_envs", "pip", spec_key)
    if os.path.isdir(dest):
        return dest
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f"{dest}.tmp.{os.getpid()}"
    cmd = [
        sys.executable, "-m", "pip", "install",
        "--no-index", "--find-links", pip_spec["wheels_dir"],
        "--target", tmp, "--quiet", *pip_spec["packages"],
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"runtime_env pip install failed: {proc.stderr.strip()[-2000:]}"
        )
    try:
        os.rename(tmp, dest)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def ensure_working_dir(key: str, data: bytes, root: str) -> str:
    """Extract (once, cached by hash) and return the directory path.
    Concurrency-safe: extraction goes to a private temp dir that is
    atomically renamed into place; a loser of the rename race uses the
    winner's copy."""
    dest = os.path.join(root, "runtime_envs", key.split(":")[-1])
    if os.path.isdir(dest):
        return dest
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f"{dest}.tmp.{os.getpid()}"
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        # another process won the race; its fully-extracted copy serves
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def local_py_paths(runtime_env: Optional[Dict[str, Any]],
                   session_root: str) -> Optional[List[str]]:
    """Local-mode resolution: py_modules already live on this filesystem,
    so their PARENT dirs go straight onto sys.path (no packaging round
    trip); pip specs still build their cached target dir."""
    if not runtime_env:
        return None
    paths = []
    for m in runtime_env.get("py_modules") or ():
        m = m.rstrip("/")
        paths.append(os.path.dirname(os.path.realpath(m)))
    if runtime_env.get("pip"):
        paths.append(ensure_pip_env(runtime_env["pip"], session_root))
    return paths or None


@contextlib.contextmanager
def applied(env_vars: Optional[Dict[str, str]] = None,
            cwd: Optional[str] = None, keep: bool = False,
            py_paths: Optional[List[str]] = None):
    """Apply env_vars/cwd/sys.path process-wide for the task's duration.
    keep=True (actor creation) leaves them in place — the dedicated actor
    worker owns its environment for the actor's lifetime. ``py_paths``
    (extracted py_modules roots + pip target dirs) are PREPENDED so they
    shadow same-named modules on the base path."""
    if not env_vars and not cwd and not py_paths:
        yield
        return
    _apply_lock.acquire()
    saved_env = {k: os.environ.get(k) for k in (env_vars or {})}
    saved_cwd = os.getcwd() if cwd else None
    added_paths = [p for p in (py_paths or []) if p not in sys.path]
    try:
        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        if cwd:
            os.chdir(cwd)
        for p in reversed(added_paths):
            sys.path.insert(0, p)
        yield
    finally:
        if keep:
            _apply_lock.release()
        else:
            try:
                for k, old in saved_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
                if saved_cwd:
                    os.chdir(saved_cwd)
                for p in added_paths:
                    try:
                        sys.path.remove(p)
                    except ValueError:
                        pass
            finally:
                _apply_lock.release()
