"""Task/actor specifications.

Reference: TaskSpecification (src/ray/common/task/task_spec.h) — the
immutable description a submitter hands the scheduler. SchedulingClass here
is the canonicalized resource demand + strategy, the same equivalence class
the reference uses to reuse worker leases
(src/ray/core_worker/transport/normal_task_submitter.cc).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


def new_id(prefix: str) -> str:
    return f"{prefix}-{os.urandom(8).hex()}"


@dataclass
class SchedulingStrategy:
    """User-facing scheduling strategies (reference:
    python/ray/util/scheduling_strategies.py)."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP | NODE_LABEL
    node_id: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[str] = None
    bundle_index: int = -1
    # NODE_LABEL: {label_key: [allowed values]}; hard filters, soft prefers
    labels_hard: Optional[Dict[str, Any]] = None
    labels_soft: Optional[Dict[str, Any]] = None


@dataclass
class TaskSpec:
    task_id: str
    func: Any  # callable (local mode) or pickled bytes (cross-process)
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    max_retries: int = 3
    retries_left: int = 3
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    # actor fields
    actor_id: Optional[str] = None  # set for actor method calls
    actor_creation: bool = False
    method_name: Optional[str] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    # per-task environment (validated dict: env_vars / working_dir)
    runtime_env: Optional[Dict[str, Any]] = None
    # streaming generator returns (reference: _raylet.pyx streaming
    # generators / num_returns="streaming"): the task's declared return
    # (output index 0) is the END MARKER — item count on success, the
    # error on failure — and yielded items stream at indices 1..n as the
    # task produces them. backpressure>0 bounds unacked in-flight items.
    streaming: bool = False
    backpressure: int = 0
    # bookkeeping
    owner_id: Optional[str] = None
    submitted_at: float = field(default_factory=time.time)
    name: str = ""

    def scheduling_class(self) -> Tuple:
        """Canonical demand signature: tasks in one class are interchangeable
        to the scheduler (lease-reuse equivalence, normal_task_submitter.cc)."""
        res = tuple(sorted((k, float(v)) for k, v in self.resources.items() if v))
        return (
            res,
            self.strategy.kind,
            self.strategy.node_id,
            self.strategy.placement_group_id,
            self.strategy.bundle_index,
        )
