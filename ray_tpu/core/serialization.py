"""Serialization: cloudpickle for closures, out-of-band buffers for arrays.

Reference: python/ray/_private/serialization.py (cloudpickle + pickle5
buffer_callback for zero-copy numpy through plasma). Same structure: pickle
protocol 5 with out-of-band buffer extraction so large numpy/jax host arrays
are carried as raw bytes (and later, placed in the shm store without a copy).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

try:  # cloudpickle ships inside `torch`-less envs too; fall back to pickle
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None


def dumps_oob(obj: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers (protocol 5)."""
    buffers: List[pickle.PickleBuffer] = []
    if cloudpickle is not None:
        data = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    else:
        data = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return data, buffers


def loads_oob(data: bytes, buffers) -> Any:
    return pickle.loads(data, buffers=buffers)


def dumps(obj: Any) -> bytes:
    """Single-buffer serialize (buffers folded in-band)."""
    if cloudpickle is not None:
        return cloudpickle.dumps(obj)
    return pickle.dumps(obj)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def pack(obj: Any) -> bytes:
    """Frame out-of-band buffers into one contiguous payload:
    [u32 npick][pickle][u32 nbuf][(u64 len, bytes)...] — the layout the shm
    object store stores verbatim, so numpy buffers deserialize as views."""
    data, buffers = dumps_oob(obj)
    out = io.BytesIO()
    out.write(len(data).to_bytes(8, "little"))
    out.write(data)
    out.write(len(buffers).to_bytes(4, "little"))
    for b in buffers:
        raw = b.raw()
        out.write(raw.nbytes.to_bytes(8, "little"))
        out.write(raw)
    return out.getvalue()


def unpack(payload) -> Any:
    """Inverse of pack(); accepts bytes or memoryview (zero-copy for arrays)."""
    mv = memoryview(payload)
    npick = int.from_bytes(mv[:8], "little")
    data = mv[8 : 8 + npick]
    off = 8 + npick
    nbuf = int.from_bytes(mv[off : off + 4], "little")
    off += 4
    buffers = []
    for _ in range(nbuf):
        ln = int.from_bytes(mv[off : off + 8], "little")
        off += 8
        buffers.append(mv[off : off + ln])
        off += ln
    return loads_oob(bytes(data), buffers)
