"""Exception hierarchy (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get() on the caller
    (reference: RayTaskError in python/ray/exceptions.py — the traceback of
    the remote execution is carried in `cause_text`)."""

    def __init__(self, message: str, cause_text: str = ""):
        super().__init__(message)
        self.cause_text = cause_text

    def __str__(self):
        base = super().__str__()
        if self.cause_text:
            return f"{base}\n\nRemote traceback:\n{self.cause_text}"
        return base


class ActorError(RayTpuError):
    """Actor-related failure."""


class ActorDiedError(ActorError):
    """The actor died before/while executing the call (reference: RayActorError)."""


class ClusterOverloadedError(RayTpuError):
    """The GCS admission controller refused the submission: this driver's
    in-system task count is at its bound (reference shape: the pushback in
    Ray's backpressure RFCs — reject loudly instead of queueing without
    bound). RETRYABLE: ``retry_after_s`` carries the server's pacing hint;
    with ``admission_pacing_enabled`` the client retries admission itself
    for up to ``admission_pacing_max_s`` before surfacing this error."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RayTpuError, TimeoutError):
    """A request's deadline expired before its handler ran, so it was shed
    (serve fast-path deadline-aware load shedding). A DELIVERED typed
    outcome, never a silent drop: the submitter's response resolves with
    this error exactly once."""


class ObjectLostError(RayTpuError):
    """Object can no longer be retrieved and could not be reconstructed
    (reference: ObjectLostError / ObjectReconstructionFailedError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get(timeout=...) expired (reference: GetTimeoutError)."""


class WorkerCrashedError(RayTpuError):
    """Worker process died mid-task (reference: WorkerCrashedError)."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to build (reference: RuntimeEnvSetupError)."""
