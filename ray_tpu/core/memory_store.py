"""In-process object store for small / inlined results.

Reference: src/ray/core_worker/store_provider/memory_store/memory_store.cc —
the core worker's in-process store holding inlined results (below
max_direct_call_object_size) and error markers, with blocking Get. The
plasma-equivalent shm store is a separate component (ray_tpu.core.shm_store);
this one is pure Python and always present.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.exceptions import GetTimeoutError
from ray_tpu.core.object_ref import ObjectRef


class _Entry:
    __slots__ = ("value", "is_exception")

    def __init__(self, value: Any, is_exception: bool = False):
        self.value = value
        self.is_exception = is_exception


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, _Entry] = {}
        self._cv = threading.Condition(self._lock)

    def put(self, ref: ObjectRef, value: Any, is_exception: bool = False) -> None:
        with self._cv:
            self._store[ref.id] = _Entry(value, is_exception)
            self._cv.notify_all()

    def list_entries(self, limit: int = 1000):
        """State-API view (reference: `ray list objects`)."""
        import sys

        out = []
        with self._lock:
            for oid, e in list(self._store.items())[:limit]:
                out.append({
                    "object_id": oid,
                    "is_exception": e.is_exception,
                    "approx_size": sys.getsizeof(e.value),
                    "type": type(e.value).__name__,
                })
        return out

    def contains(self, ref: ObjectRef) -> bool:
        with self._lock:
            return ref.id in self._store

    def try_get(self, ref: ObjectRef) -> Optional[_Entry]:
        with self._lock:
            return self._store.get(ref.id)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[_Entry]:
        """Blocking get of all refs; raises GetTimeoutError on expiry."""
        deadline = None if timeout is None else (threading.TIMEOUT_MAX if timeout < 0 else timeout)
        with self._cv:
            def ready():
                return all(r.id in self._store for r in refs)

            if not self._cv.wait_for(ready, timeout=deadline):
                raise GetTimeoutError(
                    f"get timed out after {timeout}s; "
                    f"missing {[r.id[:8] for r in refs if r.id not in self._store]}"
                )
            return [self._store[r.id] for r in refs]

    def wait(
        self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        with self._cv:
            def enough():
                return sum(1 for r in refs if r.id in self._store) >= num_returns

            self._cv.wait_for(enough, timeout=timeout)
            ready = [r for r in refs if r.id in self._store]
            not_ready = [r for r in refs if r.id not in self._store]
            return ready[:num_returns] + [], not_ready + ready[num_returns:]

    def delete(self, refs: List[ObjectRef]) -> None:
        with self._lock:
            for r in refs:
                self._store.pop(r.id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._store)
