"""ray_tpu.chaos — deterministic fault injection for the control plane.

Reference points: the reference repo proves its fault tolerance with
chaos tests (python/ray/tests/test_chaos.py + the node-killer utilities in
test_utils); this package makes the same class of testing *deterministic*:
a seeded :class:`FaultSchedule` decides, per frame of each RPC stream,
whether to drop/delay/duplicate the frame, reset the connection, enforce a
one-way partition between named endpoints, or kill a registered process at
a step — and records a byte-identical fault trace for a fixed seed.

Activation:

- per-test: ``chaos.install(FaultSchedule(seed=7, rules=[...]))`` /
  ``chaos.uninstall()`` (pair them in try/finally);
- via env: ``RAY_TPU_CHAOS_SPEC='{"seed":7,"rules":[...]}'`` — read once
  at RPC-layer import, so worker subprocesses inherit the same plane.

When nothing is installed the RPC hot path pays exactly one module-global
``is None`` check per frame (``rpc.CHAOS``); no chaos code runs.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ray_tpu.chaos.schedule import (  # noqa: F401 - public API
    HOOKS,
    KINDS,
    FaultSchedule,
    Rule,
    delay,
    drop,
    duplicate,
    kill,
    kill_at,
    partition,
    register_kill,
    reset,
    slow,
    unregister_kill,
)

ENV_SPEC = "RAY_TPU_CHAOS_SPEC"


def install(schedule: FaultSchedule) -> FaultSchedule:
    """Make ``schedule`` the process-wide active fault plane."""
    from ray_tpu.cluster import rpc as _rpc

    _rpc.CHAOS = schedule
    return schedule


def uninstall() -> None:
    """Deactivate injection (the hot-path flag goes back to None)."""
    from ray_tpu.cluster import rpc as _rpc

    _rpc.CHAOS = None


def active() -> Optional[FaultSchedule]:
    from ray_tpu.cluster import rpc as _rpc

    return _rpc.CHAOS


def install_from_env() -> Optional[FaultSchedule]:
    """Install a schedule from the ``RAY_TPU_CHAOS_SPEC`` JSON env var
    (no-op returning None when unset)."""
    spec = os.environ.get(ENV_SPEC)
    if not spec:
        return None
    return install(FaultSchedule.from_spec(json.loads(spec)))
