"""Deterministic fault schedules for the control plane.

A ``FaultSchedule`` is a seeded set of rules consulted from hook points in
``cluster/rpc.py`` (client send, server recv, server send) and from
``step()`` hooks in the test harnesses (process kills). Every decision is a
pure function of ``(seed, rule, stream, frame_index)`` — no shared RNG
state — so two runs with the same seed make identical decisions for the
nth frame of any given stream regardless of thread interleaving, and the
recorded fault trace (sorted per stream) is byte-identical across runs.

Endpoints are named: servers carry their ``name`` ("gcs", "daemon-..."),
clients carry ``name``/``peer`` labels (a daemon's node id, a driver's
worker id). Rules match endpoints with fnmatch globs, so
``reset(src="driver*", dst="gcs")`` targets every driver's GCS connection
and ``partition(src="node-3", dst="gcs")`` is a one-way partition.

Only the stdlib is used here, and nothing from ``ray_tpu.cluster`` is
imported at module level: the RPC layer guards every hook behind a single
``if CHAOS is not None`` check, so this module stays importable (and the
hot path stays zero-overhead) whether or not injection is active.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

#: hook names, for reference: client_send | server_recv | server_send |
#: step | exec (worker-side task-execution hook, see ``slow``)
HOOKS = ("client_send", "server_recv", "server_send", "step", "exec")

#: fault kinds a rule can inject
KINDS = ("drop", "delay", "duplicate", "reset", "partition", "kill", "slow")

# Process-level kill-target registry: harnesses (Cluster.add_node, soak
# scripts) register targets HERE unconditionally, so a schedule installed
# after cluster construction still finds them. Per-schedule registrations
# (FaultSchedule.register_kill) shadow these.
_KILL_TARGETS: Dict[str, Callable[[], None]] = {}


def register_kill(target: str, fn: Callable[[], None]) -> None:
    _KILL_TARGETS[target] = fn


def unregister_kill(target: str, fn: Optional[Callable] = None) -> None:
    """Remove a kill target. Pass the callable you registered to make the
    removal owner-safe: a second harness re-registering the same name must
    not have its live entry deleted by the first harness's teardown."""
    if fn is None or _KILL_TARGETS.get(target) is fn:
        _KILL_TARGETS.pop(target, None)


@dataclasses.dataclass
class Rule:
    """One fault rule. Fires on frames matching (hook, src, dst, method)
    when the trigger condition holds:

    - ``at``: exactly the ``at``-th matching frame of the stream
    - ``frm``/``until``: every frame with ``frm <= n < until`` (partitions)
    - ``p``: each frame independently with probability ``p``, decided by a
      seeded hash of the stream key and frame index (deterministic)
    """

    kind: str
    src: str = "*"
    dst: str = "*"
    method: Optional[str] = None  # None matches every method/channel
    hook: Optional[str] = None  # None matches every hook point
    p: float = 0.0
    at: Optional[int] = None
    frm: int = 0
    until: Optional[int] = None
    delay_s: float = 0.05
    target: Optional[str] = None  # kill rules: registered kill-target name
    # slow rules: execution-time multiplier injected at the worker exec
    # hook (1.0 = no-op; float("inf") wedges the task forever — the
    # gray-failure "alive but never finishes" mode)
    factor: float = 1.0

    def matches(self, hook: str, src: str, dst: str,
                method: Optional[str]) -> bool:
        # exec consults pair exclusively with slow rules: a generic
        # hook=None rule (e.g. drop(p=...)) must not fire on — or shadow —
        # the worker execution stream, and vice versa
        if (hook == "exec") != (self.kind == "slow"):
            return False
        if self.hook is not None and self.hook != hook:
            return False
        if self.method is not None and self.method != method:
            return False
        return fnmatch.fnmatchcase(src, self.src) and fnmatch.fnmatchcase(
            dst, self.dst
        )

    def fires(self, seed: int, rule_idx: int, key: Tuple, n: int) -> bool:
        if self.at is not None:
            return n == self.at
        if self.kind == "partition" or self.until is not None or self.frm:
            return n >= self.frm and (self.until is None or n < self.until)
        if self.p > 0.0:
            return _chance(seed, rule_idx, key, n) < self.p
        return False

    def to_spec(self) -> Dict:
        out = {"kind": self.kind}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name != "kind" and v != f.default:
                out[f.name] = v
        return out


def _chance(seed: int, rule_idx: int, key: Tuple, n: int) -> float:
    """Uniform [0,1) drawn purely from identity — the determinism core."""
    h = hashlib.blake2b(
        repr((seed, rule_idx, key, n)).encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


# ------------------------------------------------------- rule constructors


def drop(src: str = "*", dst: str = "*", p: float = 0.0,
         at: Optional[int] = None, method: Optional[str] = None,
         hook: Optional[str] = None) -> Rule:
    """Silently discard a frame (request, response, or push)."""
    return Rule("drop", src=src, dst=dst, p=p, at=at, method=method, hook=hook)


def delay(src: str = "*", dst: str = "*", p: float = 0.0,
          at: Optional[int] = None, delay_s: float = 0.05,
          method: Optional[str] = None, hook: Optional[str] = None) -> Rule:
    """Stall a frame for ``delay_s`` before letting it through."""
    return Rule("delay", src=src, dst=dst, p=p, at=at, delay_s=delay_s,
                method=method, hook=hook)


def duplicate(src: str = "*", dst: str = "*", p: float = 0.0,
              at: Optional[int] = None, method: Optional[str] = None,
              hook: Optional[str] = None) -> Rule:
    """Deliver a frame twice (tests at-least-once / dedupe paths)."""
    return Rule("duplicate", src=src, dst=dst, p=p, at=at, method=method,
                hook=hook)


def reset(src: str = "*", dst: str = "*", p: float = 0.0,
          at: Optional[int] = None, method: Optional[str] = None,
          hook: Optional[str] = None) -> Rule:
    """Tear the connection down mid-stream (RST-style)."""
    return Rule("reset", src=src, dst=dst, p=p, at=at, method=method,
                hook=hook)


def partition(src: str, dst: str, frm: int = 0,
              until: Optional[int] = None) -> Rule:
    """One-way partition: drop every src->dst frame with index in
    [frm, until). ``until=None`` partitions forever."""
    return Rule("partition", src=src, dst=dst, frm=frm, until=until)


def kill_at(label: str, at: int, target: str) -> Rule:
    """Kill the registered ``target`` process on the ``at``-th ``step()``
    consult carrying ``label`` (see FaultSchedule.register_kill)."""
    return Rule("kill", src=label, hook="step", at=at, target=target)


def kill(label: str = "*", p: float = 0.0, target: Optional[str] = None) -> Rule:
    return Rule("kill", src=label, hook="step", p=p, target=target)


def slow(node: str = "*", factor: float = 10.0, p: float = 1.0,
         method: Optional[str] = None, frm: int = 0,
         until: Optional[int] = None) -> Rule:
    """Gray failure: multiply task execution time on matching nodes by
    ``factor`` (consulted at the worker ``exec`` hook; ``method`` matches
    the task's function name). ``factor=float("inf")`` wedges the task
    forever — the node stays ALIVE on heartbeats while never finishing.
    Default ``p=1.0``: a gray node is slow on *every* task, not
    probabilistically."""
    return Rule("slow", src=node, hook="exec", p=p, method=method,
                frm=frm, until=until, factor=factor)


# ------------------------------------------------------------ the schedule


class FaultSchedule:
    """Seeded, deterministic fault-injection plane.

    Install with ``ray_tpu.chaos.install(schedule)``; the RPC layer then
    consults it at each hook point. Decisions and the recorded trace are
    deterministic per stream (see module docstring)."""

    def __init__(self, seed: int = 0, rules: Optional[List[Rule]] = None):
        self.seed = int(seed)
        self.rules = list(rules or ())
        self._lock = threading.Lock()
        self._counters: Dict[Tuple, int] = {}  # stream key -> frames seen
        self._records: List[Tuple] = []  # (hook, src, dst, n, method, kind)
        self._kill_targets: Dict[str, Callable[[], None]] = {}
        self.consults = 0  # total hook consults (observability/tests)

    # ------------------------------------------------------------- hooks

    def on_client_send(self, src: str, dst: str,
                       method: Optional[str]) -> Optional[Rule]:
        return self._consult("client_send", src, dst, method)

    def on_server_recv(self, src: str, dst: str,
                       method: Optional[str]) -> Optional[Rule]:
        return self._consult("server_recv", src, dst, method)

    def on_server_send(self, src: str, dst: str,
                       channel: Optional[str]) -> Optional[Rule]:
        return self._consult("server_send", src, dst, channel)

    def on_exec(self, node: str, method: Optional[str]) -> float:
        """Worker-side task-execution hook: returns the execution-delay
        factor for this task (1.0 = run at full speed). Consulted once per
        task execution; the frame counter advances per (node, method)
        stream, so decisions stay deterministic per stream like every
        other hook. The first matching slow rule wins."""
        rule = self._consult("exec", node, "*", method)
        if rule is not None and rule.kind == "slow":
            return float(rule.factor)
        return 1.0

    def step(self, label: str) -> Optional[Rule]:
        """Process-level hook (test harness loops): consults kill rules.
        A fired rule with a registered ``target`` (on this schedule, or in
        the process-level registry) runs its kill callback on a fresh
        thread (kills are slow; the calling loop must not stall)."""
        rule = self._consult("step", label, "*", None)
        if rule is not None and rule.kind == "kill" and rule.target:
            fn = self._kill_targets.get(rule.target) or _KILL_TARGETS.get(
                rule.target
            )
            if fn is not None:
                threading.Thread(
                    target=fn, daemon=True, name=f"chaos-kill-{rule.target}"
                ).start()
        return rule

    def _consult(self, hook: str, src: str, dst: str,
                 method: Optional[str]) -> Optional[Rule]:
        key = (hook, src, dst, method)
        with self._lock:
            self.consults += 1
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        for i, rule in enumerate(self.rules):
            if not rule.matches(hook, src, dst, method):
                continue
            if rule.fires(self.seed, i, key, n):
                with self._lock:
                    self._records.append(
                        (hook, src, dst, n, method or "", rule.kind)
                    )
                return rule
        return None

    # ----------------------------------------------------- kills & trace

    def register_kill(self, target: str, fn: Callable[[], None]) -> None:
        """Name a killable process; ``kill``/``kill_at`` rules reference it
        by ``target``."""
        self._kill_targets[target] = fn

    def trace(self) -> List[Tuple]:
        """Fired faults, sorted per stream: deterministic for a fixed seed
        whenever each stream sees the same frames in the same order."""
        with self._lock:
            return sorted(self._records)

    def trace_text(self) -> str:
        """The trace as bytes-comparable text (one fault per line)."""
        return "\n".join(
            f"{hook} {src}->{dst} #{n} {method} {kind}"
            for hook, src, dst, n, method, kind in self.trace()
        )

    # -------------------------------------------------------------- spec

    def to_spec(self) -> Dict:
        return {"seed": self.seed, "rules": [r.to_spec() for r in self.rules]}

    @classmethod
    def from_spec(cls, spec: Dict) -> "FaultSchedule":
        """Inverse of to_spec; the RAY_TPU_CHAOS_SPEC env payload format."""
        rules = []
        for r in spec.get("rules", ()):
            r = dict(r)
            kind = r.pop("kind")
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            rules.append(Rule(kind, **r))
        return cls(seed=int(spec.get("seed", 0)), rules=rules)
