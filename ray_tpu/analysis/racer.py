"""Hybrid happens-before data-race sanitizer for the control plane.

Two stages, one tool:

**Stage 1 (static watchlist).** :func:`extract_watchlist` reuses the
``cross-thread-field-write`` checker's extraction machinery
(:class:`~ray_tpu.analysis.checkers.CrossThreadFieldWriteChecker`) over
``cluster/`` + ``serve/`` + ``dag/`` and emits EVERY container/scalar
field reachable from >= 2 execution contexts — including the ones the
static pass considers lock-protected, together with the lock attribute
expression it credited (``locks``). Where the checker reports only
unlocked findings, the watchlist records the whole claim surface, so
the dynamic stage can *validate* the static analysis: a field the
checker believed lock-protected that races at runtime is a finding
against the static analysis itself (alias-laundered / rebound /
``__reduce__``-reconstructed lock identities are exactly what a
syntactic lock-propagation rule cannot see). ``python -m
ray_tpu.analysis --dump-watchlist`` prints it as JSON.

**Stage 2 (dynamic vector clocks).** :class:`RaceSanitizer` is a
FastTrack-style happens-before engine (adaptive epochs: per-field state
is a single ``(tid, clock)`` epoch on the common same-thread path, and
promotes to a full read vector only when reads are genuinely
concurrent; a race-free write demotes it back). Release/acquire edges
come from one shared instrumentation layer
(:mod:`ray_tpu.analysis.sanitizer` — the same wrap points the
lock-order sanitizer rides): ``threading.Lock``/``RLock``/
``Condition`` acquire+release (including ``Condition.wait``'s hidden
release/reacquire), ``Thread.start``/``join``, ``queue.Queue``
``put``/``get``, and ``ThreadPoolExecutor.submit`` /
``Future.result``. Watched fields are instrumented by an INSTALL-TIME
attribute-proxy swap on the live objects (plus a per-class
``__setattr__`` hook so rebinds re-wrap and scalar writes are seen):
the same zero-overhead-when-off ``is None`` module-global pattern as
``rpc.CHAOS``/``rpc.TRACE`` — uninstalled, no proxies exist and no
product code consults the racer at all (``CONSULTS`` stays 0,
test-asserted).

A detected race reports BOTH access stacks, both vector clocks, and
the lock set each side held, as JSONL artifacts beside the flight
recorder's (``artifacts/race-<pid>-<reason>-<n>.jsonl``). Seeded
regression teeth live in ``node_daemon.SEEDED_BUGS`` and
``fastpath.SEEDED_BUGS`` (:data:`SEEDED_RACES`): re-introduced known
bugs the racer must catch deterministically within
``run_probe(...)``'s quiescence rounds — the detection is
schedule-INsensitive (vector clocks flag the missing happens-before
edge whether or not the bad interleaving actually fired), which is
what makes the gate deterministic.

Known limits (documented, test-pinned): scalar fields get write
tracking only (attribute READS of a plain int don't pass through any
hook we own); cross-process edges (worker subprocesses, sockets) are
invisible — the racer covers the in-process control-plane threads,
which is where the thread-density lives; ``__slots__`` classes without
``__weakref__`` are skipped at attach; nested containers inside a
watched field (e.g. the sets a watched ``defaultdict(set)`` vivifies —
the vivification itself IS tracked as a write) are raw objects.
"""

from __future__ import annotations

import _thread
import ast
import importlib
import json
import os
import sys
import threading
import weakref
from collections import OrderedDict, defaultdict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.analysis import sanitizer as _san

#: THE module global (rpc.CHAOS / rpc.TRACE pattern): ``None`` = no racer
#: installed anywhere, and — because installation is what creates the
#: proxies and patches — no instrumentation exists to consult.
RACER: Optional["RaceSanitizer"] = None

#: instrumentation consult counter (proxy ops, setattr hooks, sync
#: edges). The uninstalled-zero-overhead contract is asserted on this.
CONSULTS = 0

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: stage-1 scan scope: the thread-dense control-plane packages
WATCH_SEGMENTS = ("cluster", "serve", "dag")

#: (seeded-bug name, module with the SEEDED_BUGS set, probe that must
#: catch it) — the one table the CLI, lint_gate and tests share.
SEEDED_RACES = (
    ("metrics-push-unlocked", "ray_tpu.cluster.node_daemon",
     "daemon-metrics-push"),
    ("stats-lock-alias", "ray_tpu.serve.fastpath",
     "fastpath-stats-alias"),
)


# =====================================================================
# Stage 1: static watchlist
# =====================================================================

_SCALAR_CONSTS = (int, float, bool, str, bytes, type(None))


def _scalar_fields(init) -> set:
    """``self.X = <constant>`` fields in __init__ (counters, flags,
    seqs): rebind-tracked by the dynamic stage (writes only)."""
    if init is None:
        return set()
    out = set()
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Constant)
                and isinstance(v.value, _SCALAR_CONSTS)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name
            ) and t.value.id == "self":
                out.add(t.attr)
    return out


def _locks_covering(fn, lock_attrs) -> Dict[int, frozenset]:
    """Like the checker's ``_nodes_under_lock`` but records WHICH lock
    attrs lexically cover each node (the credited-lock expression the
    watchlist carries for dynamic validation)."""
    out: Dict[int, frozenset] = {}

    def locks_of(w) -> frozenset:
        if not isinstance(w, ast.With):
            return frozenset()
        names = set()
        for item in w.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and isinstance(
                e.value, ast.Name
            ) and e.value.id == "self" and e.attr in lock_attrs:
                names.add(e.attr)
        return frozenset(names)

    def walk(node, held: frozenset):
        for child in ast.iter_child_nodes(node):
            child_held = held | locks_of(child)
            if child_held:
                out[id(child)] = child_held
                for sub in ast.walk(child):
                    out[id(sub)] = child_held
            else:
                walk(child, child_held)

    walk(fn, frozenset())
    return out


def extract_watchlist(paths: Optional[Sequence[str]] = None,
                      root: Optional[str] = None) -> List[dict]:
    """Stage 1: every container/scalar field of every class with >= 2
    execution contexts in scope, with the contexts that mutate it and
    the lock attrs the static pass credits. Pragma-suppressed mutation
    sites (``# ray-lint: disable=cross-thread-field-write``) do not
    count toward lockedness claims — same suppression semantics as the
    checker. Entries sort deterministically."""
    from ray_tpu.analysis.checkers import CrossThreadFieldWriteChecker
    from ray_tpu.analysis.core import Finding, Pragmas, iter_modules

    root = root or _REPO
    if paths is None:
        paths = [os.path.join(root, "ray_tpu", seg)
                 for seg in WATCH_SEGMENTS]
    chk = CrossThreadFieldWriteChecker()
    entries: List[dict] = []
    errors: List[str] = []
    for ctx in iter_modules(paths, root=root, errors=errors):
        pragmas = Pragmas(ctx.source)
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            entries.extend(
                _class_watch_entries(chk, ctx, cls, pragmas, Finding)
            )
    if errors:
        raise ValueError(
            "extract_watchlist: unparseable file(s): " + "; ".join(errors)
        )
    entries.sort(key=lambda e: (e["module"], e["cls"], e["field"]))
    return entries


def _class_watch_entries(chk, ctx, cls, pragmas, Finding) -> List[dict]:
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    lock_attrs = chk._lock_attrs(cls)
    containers = chk._mutable_fields(methods.get("__init__"))
    scalars = _scalar_fields(methods.get("__init__")) - containers \
        - lock_attrs
    fields = containers | scalars
    if not fields:
        return []
    roots = list(chk._context_roots(cls, methods))
    # watchlist-only widening over the checker: public entry points are a
    # distinct "caller" context (the checker stays conservative to keep
    # findings high-precision; the WATCHLIST wants reachability — e.g.
    # FastPathRouter.submit runs on arbitrary user threads)
    roots += [
        (name, "caller") for name in methods
        if not name.startswith("_") and name != "__init__"
    ]
    if len({c for _m, c in roots}) < 2:
        return []
    # effective (context, locked) per method through the same-class call
    # graph — the checker's propagation, verbatim
    reach: Dict[str, set] = {}
    work = [(m, c, False) for m, c in roots if m in methods]
    while work:
        name, context, locked = work.pop()
        eff_locked = locked or name.endswith("_locked")
        key = (context, eff_locked)
        if key in reach.setdefault(name, set()):
            continue
        reach[name].add(key)
        for callee, call_locked in chk._calls_of(methods[name], lock_attrs):
            if callee in methods:
                work.append((callee, context, eff_locked or call_locked))
    per_field: Dict[str, dict] = {}
    for name, fn in methods.items():
        if name == "__init__":
            continue
        cover = _locks_covering(fn, lock_attrs)
        for context, locked in reach.get(name, ()):
            for field, node, _in_with in chk._mutations(
                fn, fields, lock_attrs
            ):
                line = getattr(node, "lineno", 1)
                probe = Finding(
                    path=ctx.relpath, line=line, col=0,
                    check="cross-thread-field-write", message="",
                    line_text=ctx.line_text(line),
                    end_line=getattr(node, "end_lineno", None) or line,
                )
                if pragmas.suppressed(probe):
                    continue
                rec = per_field.setdefault(field, {
                    "contexts": set(), "locks": set(), "all_locked": True,
                })
                rec["contexts"].add(context)
                here = cover.get(id(node), frozenset())
                if locked or here or name.endswith("_locked"):
                    rec["locks"].update(here)
                else:
                    rec["all_locked"] = False
    out = []
    for field, rec in per_field.items():
        out.append({
            "module": ctx.relpath.replace("\\", "/"),
            "cls": cls.name,
            "field": field,
            "kind": "container" if field in containers else "scalar",
            "contexts": sorted(rec["contexts"]),
            "locked": rec["all_locked"] and bool(rec["locks"]),
            "locks": sorted("self." + a for a in rec["locks"]),
        })
    return out


# =====================================================================
# Stage 2: vector clocks (FastTrack-style adaptive epochs)
# =====================================================================


def _join(vc: Dict[int, int], other: Dict[int, int]) -> None:
    for t, c in other.items():
        if c > vc.get(t, 0):
            vc[t] = c


class _ThreadState:
    __slots__ = ("tid", "vc", "name")

    def __init__(self, tid: int, vc: Dict[int, int], name: str):
        self.tid = tid
        self.vc = vc
        self.name = name


class _FieldState:
    """FastTrack per-field state: last-write epoch, and read state that
    is an epoch on the common path, a vector only while reads are
    concurrent (promotion), reset by a race-free write (demotion)."""

    __slots__ = ("wepoch", "winfo", "repoch", "rinfo", "rvc", "rinfos")

    def __init__(self):
        self.wepoch = None
        self.winfo = None
        self.repoch = None
        self.rinfo = None
        self.rvc = None
        self.rinfos = None


#: exact container types the proxy swap covers (subclasses excluded on
#: purpose: a subclass may carry behavior the proxy would mask)
_WRAP_TYPES = {dict, list, set, deque, defaultdict, OrderedDict}

_READ_METHODS = (
    "get", "keys", "values", "items", "copy", "count", "index",
)
_WRITE_METHODS = (
    "append", "appendleft", "add", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "update", "setdefault", "extend",
    "insert", "move_to_end", "sort", "reverse",
)


def _unwrap(obj):
    """Pickle helper: a proxy serializes as its underlying container
    (an instrumented field riding an RPC payload must not leak shims
    into a peer process)."""
    return obj


class _RaceProxy:
    """Wraps one watched container; every read/write method reports to
    the racer, then delegates. Unknown attributes delegate silently.

    Each proxy carries its OWN happens-before state (races are per heap
    object): the drain-swap idiom — ``batch, self.q = self.q, []`` under
    a lock, then iterate ``batch`` outside it — is race-free because the
    swapped-out object is private, and per-slot keying would false-flag
    exactly that. The attribute slot itself is a separate location whose
    rebinds are tracked under the ``(label, field)`` key."""

    __slots__ = ("_obj", "_ikey", "_racer", "__weakref__")

    def __init__(self, obj, ikey, racer):
        object.__setattr__(self, "_obj", obj)
        object.__setattr__(self, "_ikey", ikey)
        object.__setattr__(self, "_racer", racer)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_obj"), name)

    def __repr__(self):
        return repr(object.__getattribute__(self, "_obj"))

    def __reduce__(self):
        return (_unwrap, (object.__getattribute__(self, "_obj"),))

    # dunders delegate through the OPERATION, not attribute lookup —
    # ``dict`` has no ``__bool__`` (truthiness falls back to __len__),
    # ``set`` has no ``__reversed__``, etc.

    def _ev(self, op):
        object.__getattribute__(self, "_racer")._on_access(
            object.__getattribute__(self, "_ikey"), op, holder=self
        )

    def __len__(self):
        self._ev("r")
        return len(object.__getattribute__(self, "_obj"))

    def __bool__(self):
        self._ev("r")
        return bool(object.__getattribute__(self, "_obj"))

    def __iter__(self):
        self._ev("r")
        return iter(object.__getattribute__(self, "_obj"))

    def __reversed__(self):
        self._ev("r")
        return reversed(object.__getattribute__(self, "_obj"))

    def __contains__(self, item):
        self._ev("r")
        return item in object.__getattribute__(self, "_obj")

    def __eq__(self, other):
        self._ev("r")
        if isinstance(other, _RaceProxy):
            other = object.__getattribute__(other, "_obj")
        return object.__getattribute__(self, "_obj") == other

    def __ne__(self, other):
        return not self.__eq__(other)

    # defining __eq__ in the class body would otherwise null __hash__
    __hash__ = object.__hash__

    def __getitem__(self, key):
        obj = object.__getattribute__(self, "_obj")
        # defaultdict auto-vivification: a missing-key lookup INSERTS,
        # so it must count as a write or the unlocked-shared-index bug
        # class (two threads doing `self.index[k].add(...)`) would look
        # like concurrent reads. (The vivified inner container itself
        # is a raw object — a documented limit.)
        if (isinstance(obj, defaultdict)
                and obj.default_factory is not None and key not in obj):
            self._ev("w")
        else:
            self._ev("r")
        return obj[key]

    def __setitem__(self, key, value):
        self._ev("w")
        object.__getattribute__(self, "_obj")[key] = value

    def __delitem__(self, key):
        self._ev("w")
        del object.__getattribute__(self, "_obj")[key]

    def __ior__(self, other):
        self._ev("w")
        obj = object.__getattribute__(self, "_obj")
        if isinstance(other, _RaceProxy):
            other = object.__getattribute__(other, "_obj")
        obj |= other
        return self

    def __iadd__(self, other):
        self._ev("w")
        obj = object.__getattribute__(self, "_obj")
        if isinstance(other, _RaceProxy):
            other = object.__getattribute__(other, "_obj")
        obj += other
        return self


def _proxy_method(name: str, op: str):
    def method(self, *a, **k):
        racer = object.__getattribute__(self, "_racer")
        racer._on_access(object.__getattribute__(self, "_ikey"), op,
                         holder=self)
        return getattr(object.__getattribute__(self, "_obj"), name)(*a, **k)
    method.__name__ = name
    return method


for _n in _READ_METHODS:
    setattr(_RaceProxy, _n, _proxy_method(_n, "r"))
for _n in _WRITE_METHODS:
    setattr(_RaceProxy, _n, _proxy_method(_n, "w"))
del _n


class _Attached:
    __slots__ = ("label",)

    def __init__(self, label: str):
        self.label = label


class RaceSanitizer:
    """The dynamic stage. ``install()`` patches the sync seams and
    proxy-swaps every watched field on live (and future) instances;
    ``uninstall()`` restores everything. One racer may be active at a
    time (module global ``RACER``)."""

    def __init__(self, watchlist: Optional[List[dict]] = None,
                 stack_depth: int = 10, max_races: int = 64):
        self.watchlist = (extract_watchlist() if watchlist is None
                          else list(watchlist))
        self.stack_depth = stack_depth
        self.max_races = max_races
        self.races: List[dict] = []
        self.unresolved: List[Tuple[dict, str]] = []
        # raw locks only: these are taken inside listener callbacks and
        # proxy ops — a wrapped lock here would recurse into the seam
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._next_tid = 0
        self._thread_states: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._lock_vcs: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._chan_vcs: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._fields: Dict[Tuple[str, str], _FieldState] = {}
        # per-container-object state (see _RaceProxy: races are per heap
        # object; the attribute slot is its own location in _fields)
        self._obj_states: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._static: Dict[Tuple[str, str], dict] = {}
        self._attached: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._class_fields: Dict[type, Dict[str, dict]] = {}
        self._class_counts: Dict[str, int] = {}
        self._patched_setattr: List[Tuple[type, Any]] = []
        self._seen_races: set = set()
        self._installed = False

    # --------------------------------------------------- install / undo

    def install(self) -> "RaceSanitizer":
        global RACER
        if self._installed:
            return self
        if RACER is not None:
            raise RuntimeError("a RaceSanitizer is already installed")
        self._resolve_watchlist()
        RACER = self
        self._installed = True
        _san.add_listener(self)
        _patch_runtime()
        for cls, fields in self._class_fields.items():
            self._patch_class(cls, fields)
        self._scan_existing()
        return self

    def uninstall(self) -> None:
        global RACER
        if not self._installed:
            return
        RACER = None
        self._installed = False
        for cls, orig in self._patched_setattr:
            cls.__setattr__ = orig
        self._patched_setattr.clear()
        # unwrap live proxies: uninstalled means NO proxies exist
        with self._mu:
            objs = list(self._attached.keys())
        for obj in objs:
            fields = self._class_fields.get(type(obj), ())
            for field in fields:
                cur = getattr(obj, field, None)
                if isinstance(cur, _RaceProxy):
                    object.__setattr__(
                        obj, field, object.__getattribute__(cur, "_obj")
                    )
        _unpatch_runtime()
        _san.remove_listener(self)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    def _resolve_watchlist(self) -> None:
        for e in self.watchlist:
            modname = e["module"].replace("\\", "/")
            if modname.endswith(".py"):
                modname = modname[:-3]
            modname = modname.replace("/", ".")
            try:
                mod = importlib.import_module(modname)
                cls = getattr(mod, e["cls"])
                # @remote-decorated classes bind the module name to an
                # ActorClass wrapper; the instances whose fields we
                # watch are of the class INSIDE it
                if not isinstance(cls, type):
                    cls = getattr(cls, "_cls", cls)
                if not isinstance(cls, type):
                    raise TypeError(
                        f"{e['cls']} resolves to {type(cls).__name__}, "
                        "not a class"
                    )
            except Exception as ex:  # noqa: BLE001 - report, don't die
                self.unresolved.append((e, f"{type(ex).__name__}: {ex}"))
                continue
            self._class_fields.setdefault(cls, {})[e["field"]] = e

    def _patch_class(self, cls: type, fields: Dict[str, dict]) -> None:
        orig = cls.__setattr__
        racer = self

        def __setattr__(obj, name, value, _orig=orig, _fields=fields):
            r = RACER
            if r is racer and name in _fields:
                value = r._intercept_setattr(obj, name, value)
            _orig(obj, name, value)

        cls.__setattr__ = __setattr__
        self._patched_setattr.append((cls, orig))

    def _scan_existing(self) -> None:
        import gc

        watched = tuple(self._class_fields)
        if not watched:
            return
        for obj in gc.get_objects():
            if type(obj) in self._class_fields:
                self._attach(obj)

    # -------------------------------------------------------- attaching

    def _attach(self, obj) -> Optional[_Attached]:
        cls = type(obj)
        fields = self._class_fields.get(cls)
        if fields is None:
            return None
        with self._mu:
            try:
                rec = self._attached.get(obj)
            except TypeError:
                return None  # unhashable
            if rec is not None:
                return rec
            n = self._class_counts.get(cls.__name__, 0)
            self._class_counts[cls.__name__] = n + 1
            rec = _Attached(f"{cls.__name__}#{n}")
            try:
                self._attached[obj] = rec
            except TypeError:
                return None  # no __weakref__ (slots class): skip
            for field, entry in fields.items():
                self._static[(rec.label, field)] = entry
        for field in fields:
            v = getattr(obj, field, None)
            if type(v) in _WRAP_TYPES:
                object.__setattr__(
                    obj, field,
                    _RaceProxy(v, (rec.label, field), self),
                )
        return rec

    def _intercept_setattr(self, obj, name, value):
        global CONSULTS
        CONSULTS += 1
        rec = self._attach(obj)
        if rec is None:
            return value
        ikey = (rec.label, name)
        self._on_access(ikey, "w")
        if type(value) in _WRAP_TYPES:
            value = _RaceProxy(value, ikey, self)
        return value

    # ----------------------------------------------------- thread state

    def _state(self) -> Optional[_ThreadState]:
        """The calling thread's vector-clock state, or ``None`` while
        the thread is still bootstrapping. ``threading.current_thread``
        is OFF LIMITS here: called from the lock-acquire callback it
        would mint a ``_DummyThread`` whose ``__init__`` allocates an
        (instrumented) Event and recurses forever — a thread's own
        ``_started.set()`` fires BEFORE CPython registers it in
        ``threading._active``. Events from that bootstrap window (and
        from foreign/dummy threads) are skipped; the thread's real
        state is created on its first event after registration, which
        still carries the ``_racer_parent`` start-edge snapshot."""
        tls = self._tls
        st = getattr(tls, "st", None)
        if st is not None:
            return st
        if getattr(tls, "making", False):
            return None
        tls.making = True
        try:
            th = threading._active.get(threading.get_ident())
            if th is None:
                return None
            with self._mu:
                tid = self._next_tid
                self._next_tid += 1
            vc: Dict[int, int] = {}
            parent = getattr(th, "_racer_parent", None)
            if parent is not None and parent[0] is self:
                vc.update(parent[1])
            vc[tid] = vc.get(tid, 0) + 1
            st = _ThreadState(tid, vc, th.name)
            tls.st = st
            with self._mu:
                try:
                    self._thread_states[th] = st
                except TypeError:
                    pass
            return st
        finally:
            tls.making = False

    def _fork(self) -> Optional[Dict[int, int]]:
        """Snapshot the current thread's clock and advance it (the
        release half of a release/acquire edge)."""
        st = self._state()
        if st is None:
            return None
        snap = dict(st.vc)
        st.vc[st.tid] += 1
        return snap

    def _join_snapshot(self, snap: Optional[Dict[int, int]]) -> None:
        st = self._state()
        if st is not None and snap:
            _join(st.vc, snap)

    def _join_thread(self, thread) -> None:
        with self._mu:
            st = self._thread_states.get(thread)
        if st is not None:
            self._join_snapshot(dict(st.vc))

    # ------------------------------------------------ sync-object edges

    def on_lock_created(self, lock, site) -> None:  # seam listener
        pass

    def on_acquire(self, lock, site, held) -> None:  # seam listener
        if not self._installed:
            return
        global CONSULTS
        CONSULTS += 1
        st = self._state()
        if st is None:
            return
        with self._mu:
            lvc = self._lock_vcs.get(lock)
        if lvc:
            _join(st.vc, lvc)

    def on_release(self, lock, site) -> None:  # seam listener
        if not self._installed:
            return
        global CONSULTS
        CONSULTS += 1
        st = self._state()
        if st is None:
            return
        snap = dict(st.vc)
        with self._mu:
            self._lock_vcs[lock] = snap
        st.vc[st.tid] += 1

    def _chan_send(self, chan) -> None:
        if not self._installed:
            return
        global CONSULTS
        CONSULTS += 1
        st = self._state()
        if st is None:
            return
        with self._mu:
            vc = self._chan_vcs.get(chan)
            if vc is None:
                vc = self._chan_vcs[chan] = {}
            _join(vc, st.vc)
        st.vc[st.tid] += 1

    def _chan_recv(self, chan) -> None:
        if not self._installed:
            return
        global CONSULTS
        CONSULTS += 1
        st = self._state()
        if st is None:
            return
        with self._mu:
            vc = self._chan_vcs.get(chan)
            snap = dict(vc) if vc else None
        if snap:
            _join(st.vc, snap)

    # --------------------------------------------------- access checks

    def _stack(self) -> Tuple[Tuple[str, int, str], ...]:
        f = sys._getframe(2)
        out = []
        here = os.path.dirname(os.path.abspath(__file__))
        while f is not None and len(out) < self.stack_depth:
            fn = f.f_code.co_filename
            if not (os.path.dirname(fn) == here
                    and os.path.basename(fn) in (
                        "racer.py", "sanitizer.py")):
                rel = fn
                if rel.startswith(_REPO + os.sep):
                    rel = rel[len(_REPO) + 1:]
                out.append((rel.replace("\\", "/"), f.f_lineno,
                            f.f_code.co_name))
            f = f.f_back
        return tuple(out)

    def _access_info(self, st: _ThreadState, epoch) -> dict:
        return {
            "thread": st.name,
            "tid": st.tid,
            "clock": epoch[1],
            "vc": {str(t): c for t, c in sorted(st.vc.items())},
            "locks": ["%s:%d" % s for s in _san.held_sites()],
            "stack": ["%s:%d %s" % fr for fr in self._stack()],
        }

    def _on_access(self, ikey: Tuple[str, str], op: str,
                   holder=None) -> None:
        # a proxy can outlive uninstall (e.g. a drained snapshot a
        # thread is still iterating): once uninstalled, locks are raw
        # again — recording through this engine would manufacture
        # phantom races and break the 0-consults contract
        if not self._installed:
            return
        global CONSULTS
        CONSULTS += 1
        tls = self._tls
        if getattr(tls, "busy", False):
            return
        tls.busy = True
        try:
            st = self._state()
            if st is None:
                return
            epoch = (st.tid, st.vc[st.tid])
            with self._mu:
                if holder is not None:
                    fs = self._obj_states.get(holder)
                    if fs is None:
                        fs = self._obj_states[holder] = _FieldState()
                else:
                    fs = self._fields.get(ikey)
                    if fs is None:
                        fs = self._fields[ikey] = _FieldState()
                if op == "w":
                    if fs.wepoch == epoch and fs.rvc is None \
                            and fs.repoch is None:
                        return  # FastTrack same-epoch fast path
                    self._check_write(ikey, fs, st, epoch)
                else:
                    if fs.repoch == epoch or (
                        fs.rvc is not None
                        and fs.rvc.get(st.tid) == epoch[1]
                    ):
                        return  # same-epoch read
                    self._check_read(ikey, fs, st, epoch)
        finally:
            tls.busy = False

    def _check_write(self, ikey, fs, st, epoch) -> None:
        if fs.rvc is not None:
            for t, c in fs.rvc.items():
                if t != st.tid and c > st.vc.get(t, 0):
                    self._record(ikey, "read-write",
                                 fs.rinfos.get(t), st, epoch)
        elif fs.repoch is not None:
            t, c = fs.repoch
            if t != st.tid and c > st.vc.get(t, 0):
                self._record(ikey, "read-write", fs.rinfo, st, epoch)
        if fs.wepoch is not None:
            t, c = fs.wepoch
            if t != st.tid and c > st.vc.get(t, 0):
                self._record(ikey, "write-write", fs.winfo, st, epoch)
        fs.wepoch = epoch
        fs.winfo = self._access_info(st, epoch)
        # demotion: a write resets read state (FastTrack WrShared)
        fs.rvc = fs.rinfos = fs.repoch = fs.rinfo = None

    def _check_read(self, ikey, fs, st, epoch) -> None:
        if fs.wepoch is not None:
            t, c = fs.wepoch
            if t != st.tid and c > st.vc.get(t, 0):
                self._record(ikey, "write-read", fs.winfo, st, epoch)
        if fs.rvc is None:
            if (fs.repoch is None or fs.repoch[0] == st.tid
                    or fs.repoch[1] <= st.vc.get(fs.repoch[0], 0)):
                fs.repoch = epoch
                fs.rinfo = self._access_info(st, epoch)
            else:
                # promotion: two genuinely concurrent readers
                self._record_promote(fs, st, epoch)
        else:
            fs.rvc[st.tid] = epoch[1]
            fs.rinfos[st.tid] = self._access_info(st, epoch)

    def _record_promote(self, fs, st, epoch) -> None:
        fs.rvc = {fs.repoch[0]: fs.repoch[1], st.tid: epoch[1]}
        fs.rinfos = {fs.repoch[0]: fs.rinfo,
                     st.tid: self._access_info(st, epoch)}
        fs.repoch = None
        fs.rinfo = None

    def _record(self, ikey, kind, prior: Optional[dict],
                st: _ThreadState, epoch) -> None:
        label, field = ikey
        cur = self._access_info(st, epoch)
        prior = prior or {}
        key = (label, field, kind,
               tuple(prior.get("stack", ())[:1]),
               tuple(cur["stack"][:1]))
        if key in self._seen_races or len(self.races) >= self.max_races:
            return
        self._seen_races.add(key)
        entry = self._static.get(ikey, {})
        race = {
            "field": f"{label}.{field}",
            "kind": kind,
            "prior": prior,
            "current": cur,
            "static": {
                "module": entry.get("module"),
                "locked": entry.get("locked", False),
                "locks": entry.get("locks", []),
                "contexts": entry.get("contexts", []),
            },
            "static_claim_violated": bool(entry.get("locked")),
        }
        if race["static_claim_violated"]:
            race["suggestion"] = (
                "the static pass credited %s as protecting this field, "
                "but the accesses were not serialized at runtime: lock "
                "identity is laundered through an alias/rebind/"
                "__reduce__ path the syntactic lock-propagation rule "
                "cannot see — fix the locking, then teach the checker "
                "the propagation shape" % (entry.get("locks") or ["?"],)
            )
        self.races.append(race)

    # -------------------------------------------------------- reporting

    @property
    def found(self) -> bool:
        return bool(self.races)

    def report(self) -> dict:
        return {
            "kind": "race-report",
            "races": list(self.races),
            "watched_classes": sorted(
                getattr(c, "__name__", str(c)) for c in self._class_fields
            ),
            "watched_fields": len(
                {(e["cls"], e["field"]) for e in self.watchlist}
            ),
            "unresolved": [
                {"entry": e, "error": err} for e, err in self.unresolved
            ],
        }

    def format_races(self) -> str:
        lines = []
        for r in self.races:
            lines.append(f"RACE {r['kind']} on {r['field']} "
                         f"(static locked={r['static']['locked']} "
                         f"via {r['static']['locks']})")
            for side in ("prior", "current"):
                a = r[side]
                lines.append(f"  {side}: thread={a.get('thread')} "
                             f"clock={a.get('tid')}@{a.get('clock')} "
                             f"locks={a.get('locks')}")
                for fr in a.get("stack", ())[:4]:
                    lines.append(f"    {fr}")
            if r.get("suggestion"):
                lines.append(f"  note: {r['suggestion']}")
        return "\n".join(lines)

    def write_report(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=2, sort_keys=True)
            f.write("\n")

    _dump_seq = 0

    def dump(self, reason: str = "race",
             out_dir: Optional[str] = None) -> str:
        """Flight-recorder-style artifact: JSONL, one header line then
        one line per race, under ``artifacts/`` (or
        ``$RAY_TPU_FLIGHTREC_DIR``) as
        ``race-<pid>-<reason>-<n>.jsonl``."""
        out_dir = out_dir or os.environ.get(
            "RAY_TPU_FLIGHTREC_DIR", "artifacts"
        )
        os.makedirs(out_dir, exist_ok=True)
        RaceSanitizer._dump_seq += 1
        path = os.path.join(
            out_dir,
            f"race-{os.getpid()}-{reason}-{RaceSanitizer._dump_seq}.jsonl",
        )
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({
                "kind": "race-report", "races": len(self.races),
                "reason": reason,
            }, sort_keys=True) + "\n")
            for r in self.races:
                f.write(json.dumps(r, sort_keys=True) + "\n")
        return path


# =====================================================================
# runtime seam patches (Thread / Queue / executor)
# =====================================================================

_runtime_orig: Optional[dict] = None


def _patch_runtime() -> None:
    global _runtime_orig
    if _runtime_orig is not None:
        return
    import concurrent.futures as cf
    import queue as queue_mod

    orig = {
        "thread_start": threading.Thread.start,
        "thread_join": threading.Thread.join,
        "queue_put": queue_mod.Queue.put,
        "queue_get": queue_mod.Queue.get,
        "submit": cf.ThreadPoolExecutor.submit,
        "result": cf.Future.result,
    }

    def start(self):
        r = RACER
        if r is not None:
            snap = r._fork()
            if snap is not None:
                self._racer_parent = (r, snap)
        return orig["thread_start"](self)

    def join(self, timeout=None):
        orig["thread_join"](self, timeout)
        r = RACER
        if r is not None and not self.is_alive():
            r._join_thread(self)

    def put(self, item, *a, **k):
        r = RACER
        if r is not None:
            r._chan_send(self)
        return orig["queue_put"](self, item, *a, **k)

    def get(self, *a, **k):
        item = orig["queue_get"](self, *a, **k)
        r = RACER
        if r is not None:
            r._chan_recv(self)
        return item

    def submit(self, fn, *args, **kwargs):
        r = RACER
        if r is None:
            return orig["submit"](self, fn, *args, **kwargs)
        snap = r._fork() or {}
        box: dict = {}

        def task(*a, **k):
            r2 = RACER
            if r2 is not None:
                r2._join_snapshot(snap)
            try:
                return fn(*a, **k)
            finally:
                if r2 is not None:
                    box["vc"] = r2._fork()

        fut = orig["submit"](self, task, *args, **kwargs)
        fut._racer_done = box
        return fut

    def result(self, timeout=None):
        try:
            return orig["result"](self, timeout)
        finally:
            r = RACER
            box = getattr(self, "_racer_done", None)
            if r is not None and box and "vc" in box:
                r._join_snapshot(box["vc"])

    threading.Thread.start = start
    threading.Thread.join = join
    queue_mod.Queue.put = put
    queue_mod.Queue.get = get
    cf.ThreadPoolExecutor.submit = submit
    cf.Future.result = result
    _runtime_orig = orig


def _unpatch_runtime() -> None:
    global _runtime_orig
    if _runtime_orig is None:
        return
    import concurrent.futures as cf
    import queue as queue_mod

    threading.Thread.start = _runtime_orig["thread_start"]
    threading.Thread.join = _runtime_orig["thread_join"]
    queue_mod.Queue.put = _runtime_orig["queue_put"]
    queue_mod.Queue.get = _runtime_orig["queue_get"]
    cf.ThreadPoolExecutor.submit = _runtime_orig["submit"]
    cf.Future.result = _runtime_orig["result"]
    _runtime_orig = None


# =====================================================================
# seeded-bug probes (the regression teeth)
# =====================================================================


class ProbeResult:
    def __init__(self, name: str, seeded: Tuple[str, ...],
                 detected: bool, rounds: int, races: List[dict],
                 unresolved: List):
        self.name = name
        self.seeded = seeded
        self.detected = detected
        self.rounds = rounds
        self.races = races
        self.unresolved = unresolved

    def summary(self) -> str:
        state = (f"RACE after {self.rounds} round(s)" if self.detected
                 else f"clean after {self.rounds} round(s)")
        seed = f" [seeded: {','.join(self.seeded)}]" if self.seeded else ""
        return (f"racer:{self.name}: {state}, "
                f"{len(self.races)} race(s){seed}")


def _barrier_pair(fn_a, fn_b) -> None:
    """One quiescence round: run two REAL code paths on two fresh
    threads released by one barrier. Happens-before between the two
    accesses then comes ONLY from locks the paths themselves take — the
    detection is schedule-insensitive, hence deterministic."""
    go = threading.Event()
    errs: List[BaseException] = []

    def wrap(fn):
        def run():
            go.wait(5.0)
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errs.append(e)
        return run

    t1 = threading.Thread(target=wrap(fn_a), name="racer-probe-a")
    t2 = threading.Thread(target=wrap(fn_b), name="racer-probe-b")
    t1.start()
    t2.start()
    go.set()
    t1.join(10.0)
    t2.join(10.0)
    if errs:
        raise errs[0]


def _probe_daemon_metrics(_round: int) -> None:
    """node_daemon layer: a worker's ``rpc_metrics_push`` (rpc-handler
    loop) racing the heartbeat thread's drain of ``_worker_metrics`` —
    the exact field/thread pair one of PR 6's 21 node_daemon lock fixes
    covered. Drives the REAL methods on a minimal instance."""
    import time as _time

    from ray_tpu.cluster.node_daemon import NodeDaemon

    d = object.__new__(NodeDaemon)
    d._lock = threading.Lock()
    d._worker_metrics = []

    def drain_until_seen():
        # drain-and-iterate until the pushed delta shows up: the drain
        # that picks it up iterates exactly the object the push wrote,
        # so the (write, read) pair lands on one heap object no matter
        # which side of a swap the push hit — detection stays
        # deterministic under per-object race state
        for _ in range(200):
            if list(NodeDaemon._drain_worker_metrics(d)):
                return
            _time.sleep(0.005)
        raise AssertionError("pushed delta never drained")

    _barrier_pair(
        lambda: NodeDaemon.rpc_metrics_push(d, {"delta": {"m": 1}}, None),
        drain_until_seen,
    )


def _probe_fastpath_stats(_round: int) -> None:
    """serve layer: two submitter threads bumping ``FastPathRouter``
    gate counters through the REAL ``_bump``. Clean code serializes on
    ``_stats_lock``; the seeded alias-laundered lock makes each bump
    hold a DIFFERENT lock object — statically invisible (the ``with
    self._stats_lock`` text is unchanged), dynamically a race."""
    from ray_tpu.serve.fastpath import FastPathRouter

    r = object.__new__(FastPathRouter)
    r._stats_lock = threading.Lock()
    r.stats = {"submitted": 0, "completed": 0}
    _barrier_pair(
        lambda: FastPathRouter._bump(r, "submitted"),
        lambda: FastPathRouter._bump(r, "completed"),
    )


RACE_PROBES = {
    "daemon-metrics-push": _probe_daemon_metrics,
    "fastpath-stats-alias": _probe_fastpath_stats,
}

#: watchlist classes each probe exercises (the probe installs a racer
#: scoped to them so unrelated background threads stay quiet)
_PROBE_CLASSES = {
    "daemon-metrics-push": ("NodeDaemon",),
    "fastpath-stats-alias": ("FastPathRouter",),
}


def _seed_sets(names: Sequence[str]):
    """(module SEEDED_BUGS set, prior contents) per module touched.
    Unknown names are an error: silently ignoring a typo'd seed would
    make a never-armed run read as 'seeded and clean'."""
    known = {bug for bug, _m, _p in SEEDED_RACES}
    unknown = [n for n in names if n not in known]
    if unknown:
        raise ValueError(
            f"unknown seeded race(s) {unknown}; have {sorted(known)}"
        )
    touched = []
    for bug, modname, _probe in SEEDED_RACES:
        mod = importlib.import_module(modname)
        touched.append((mod.SEEDED_BUGS, set(mod.SEEDED_BUGS)))
        if bug in names:
            mod.SEEDED_BUGS.add(bug)
    return touched


def run_probe(name: str, seeded_bugs: Sequence[str] = (),
              rounds: int = 3,
              watchlist: Optional[List[dict]] = None) -> ProbeResult:
    """Run one probe for up to ``rounds`` quiescence rounds (stop as
    soon as a race is found). With a seeded bug armed the racer must
    detect in round 1 — the gate bar lint_gate enforces."""
    if name not in RACE_PROBES:
        raise ValueError(
            f"unknown race probe {name!r}; have {sorted(RACE_PROBES)}"
        )
    wl = watchlist if watchlist is not None else extract_watchlist()
    scoped = [e for e in wl if e["cls"] in _PROBE_CLASSES[name]]
    prev = _seed_sets(seeded_bugs)
    racer = RaceSanitizer(watchlist=scoped)
    ran = 0
    try:
        racer.install()
        for i in range(rounds):
            ran = i + 1
            RACE_PROBES[name](i)
            if racer.found:
                break
    finally:
        racer.uninstall()
        for bugset, before in prev:
            bugset.clear()
            bugset.update(before)
    return ProbeResult(
        name, tuple(seeded_bugs), racer.found, ran,
        list(racer.races), list(racer.unresolved),
    )
