"""Deterministic control-plane model checker (interleaving exploration).

PR 3's invariant tracer verifies the protocol invariants on interleavings
that *happen* to occur in live runs and chaos soaks. This module
*searches* the interleaving space instead, in the style of Loom/Shuttle:
the real :class:`~ray_tpu.cluster.gcs.GcsServer` handler object is
instantiated behind a **virtual runtime** (see ``cluster/runtime.py``) —
no sockets, no threads, a step-counted clock — together with N simulated
daemon/driver peers. Every pending RPC delivery, push delivery, task
execution, scheduler round, connection drop, and 2PC finalizer is a
*step* on a controlled queue; a **schedule** is the sequence of steps
chosen at each decision point. The explorer then:

- enumerates schedules with a bounded-depth DFS, pruned
  persistent-set/sleep-set style: an unchosen alternative is only
  branched on when it *conflicts* (shares an entity footprint — task id,
  node id, pg id, actor id, object id, or the global scheduler) with a
  step that ran before its own turn in the current schedule — adjacent
  independent steps commute, so one of the two orders suffices;
- samples seeded-random schedules beyond the DFS bound (same-seed runs
  are byte-identical);
- pipes every explored schedule through the :class:`ProtocolTracer` +
  ``check_trace`` invariants (exactly-once, capacity conservation, PG
  2PC legality, exec-seq monotonicity, borrow/object lifecycle), plus
  handler crashes and per-scenario postconditions;
- shrinks any violating schedule to a minimal reproducer (greedy
  truncation + delta-debugging over step labels) and writes it to a
  replay file that ``python -m ray_tpu.analysis --replay <file>``
  re-executes deterministically.

The scenario library covers the known-hard corners: node kill +
reconnect with instance stamps, watchdog resend races, PG prepare/commit
vs node death (the 2PC fault hook is an interleave point, so death can
land *between* the phases), dag register vs driver disconnect, and actor
kill/creation/replay races. ``gcs.SEEDED_BUGS`` re-introduces known,
fixed bugs so the harness can prove it still finds and shrinks them.

Honesty notes: the conflict relation is an over-approximation by entity
footprint (scheduler rounds conflict with everything), so pruning is
sound with respect to it but the footprint annotations themselves are
hand-written per step kind; the simulated peers implement the *fixed*
daemon/driver protocol (same trace events as the real ones), so the
object under test is the GCS handler protocol, not daemon internals.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time as _time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ray_tpu.analysis.invariants import (
    InvariantChecker,
    ProtocolTracer,
    Violation,
)

#: schedule entry meaning "resume the step paused at an interleave point"
CONTINUE = "::continue"

#: conflict wildcard: a step with this key conflicts with every step
GLOBAL_KEY = "*"


# ---------------------------------------------------------------- tracing


class BufTracer(ProtocolTracer):
    """In-memory ProtocolTracer: same Lamport clocking and event shapes,
    but records land in a list instead of a JSONL file (10k+ schedules
    per exploration must not pay a file open/flush each)."""

    def __init__(self):  # noqa: D107 - deliberately no super().__init__
        self.path = None
        self._lock = threading.Lock()
        self._clock = 0
        self._pid = os.getpid()
        self.closed = False
        self.records: List[Dict[str, Any]] = []

    def _emit(self, rec: Dict[str, Any]) -> int:
        with self._lock:
            self._clock += 1
            rec["c"] = self._clock
            rec["pid"] = self._pid
            if not self.closed:
                self.records.append(rec)
            return self._clock

    def close(self) -> None:
        with self._lock:
            self.closed = True


def interleaving_coverage(
    events: Sequence[Dict[str, Any]], dst: str = "gcs"
) -> Set[Tuple[str, str]]:
    """Distinct ordered adjacent handler pairs observed at ``dst`` in a
    protocol trace: the coverage language the explorer and
    ``scripts/chaos_soak.py`` share — a soak that never produced the
    ordering (m1, m2) never tested it, regardless of fault count."""
    methods = [
        str(ev.get("m"))
        for ev in events
        if ev.get("t") == "recv" and ev.get("dst") == dst and ev.get("m")
    ]
    return set(zip(methods, methods[1:]))


# ------------------------------------------------------------ world parts


class ScheduleDiverged(Exception):
    """A replayed schedule named a step that is not enabled — the
    schedule does not belong to this scenario/seed (or a shrink candidate
    removed a step its suffix depended on)."""


class VirtualClock:
    def __init__(self, start: float = 1_000_000.0):
        self.t = float(start)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclasses.dataclass
class Step:
    label: str
    fn: Callable[[], None]
    keys: FrozenSet[str]
    #: label that must have executed before this step becomes enabled
    #: (models per-connection FIFO, e.g. actor-call submission order)
    after: Optional[str] = None


class VirtualConn:
    """Stand-in for rpc.ServerConn: identity + handler scratch meta.
    Conn ids are WORLD-local (not process-global like ServerConn's):
    step labels embed them, and labels must be byte-identical across
    re-executions for replay/shrinking to work."""

    def __init__(self, peer: "SimPeer"):
        world = peer.world
        world._next_conn_id += 1
        self.conn_id = world._next_conn_id
        self.meta: Dict[str, Any] = {}
        self.closed = False
        self.peer = peer

    def peer_label(self) -> str:
        return (
            self.meta.get("node_id")
            or self.meta.get("driver_id")
            or f"conn{self.conn_id}"
        )


class _VirtualFuture:
    """Minimal concurrent-future look-alike for the virtual 2PC client
    (resolved synchronously; ``result(timeout)`` never blocks)."""

    def __init__(self, value=None, exc=None):
        self._value = value
        self._exc = exc

    def result(self, timeout=None):
        if self._exc is not None:
            raise self._exc
        return self._value

    def done(self) -> bool:
        return True


class VirtualLoop:
    """The ``server.loop`` surface the GCS touches:
    ``run_in_executor(None, fn)`` becomes a schedulable step."""

    def __init__(self, world: "World"):
        self.world = world

    def run_in_executor(self, _executor, fn):
        from concurrent.futures import Future

        fut: Future = Future()

        def run():
            try:
                fut.set_result(fn())
            except Exception as e:  # noqa: BLE001 - surfaced as a finding
                fut.set_result(None)
                self.world.crash("gcs-blocking", e)

        self.world.enqueue("gcs:blocking", run, keys={GLOBAL_KEY})
        return fut


class VirtualServer:
    """RpcServer stand-in the GCS drives through the runtime seam: pushes
    and broadcasts become schedulable delivery steps (or synchronous
    record-only deliveries for inert channels)."""

    def __init__(self, world: "World", handler, on_disconnect, name: str):
        self.world = world
        self.handler = handler
        self.on_disconnect = on_disconnect
        self.name = name
        self.conns: Dict[int, VirtualConn] = {}
        self.loop = VirtualLoop(world)

    def start(self) -> int:
        return 0

    def stop(self) -> None:
        pass

    def send_push(self, conn: VirtualConn, channel: str, data: Any) -> None:
        self.world.deliver_push(conn, channel, data)

    def broadcast(self, channel: str, data: Any, filter_fn=None) -> None:
        for conn in list(self.conns.values()):
            if filter_fn is None or filter_fn(conn):
                self.world.deliver_push(conn, channel, data)

    def call_soon(self, fn, *args) -> None:
        fn(*args)


class VirtualRuntime:
    """cluster/runtime.py seam implementation backed by a World."""

    threaded = False

    def __init__(self, world: "World"):
        self.world = world

    def now(self) -> float:
        return self.world.clock.now()

    def make_server(self, handler, host, port, on_disconnect, name):
        server = VirtualServer(self.world, handler, on_disconnect, name)
        self.world.server = server
        return server

    def make_daemon_client(self, addr, port, node_id):
        d = self.world.daemons.get(node_id)
        return None if d is None else d.client

    def spawn(self, name: str, fn) -> None:
        self.world.enqueue(f"gcs:spawn:{name}", fn, keys={GLOBAL_KEY})

    def kick(self, gcs) -> None:
        self.world.kick()


# ---------------------------------------------------------------- chooser


class Chooser:
    """Drives every scheduling decision of one world execution.

    - ``prefix``: labels to follow first (DFS branch / replay / shrink);
    - after the prefix: uniform-random picks under ``rng`` if given, else
      the deterministic default (the oldest enabled step — program
      order);
    - ``stop_after``: end the run when the prefix is exhausted instead of
      running the default tail (shrinking + minimal replays).
    """

    def __init__(self, prefix: Sequence[str] = (), rng=None,
                 stop_after: bool = False):
        self.prefix = list(prefix)
        self.rng = rng
        self.stop_after = stop_after
        self.i = 0

    def choose(self, options: Tuple[str, ...],
               at_interleave: bool) -> Optional[str]:
        if self.i < len(self.prefix):
            c = self.prefix[self.i]
            if c not in options:
                raise ScheduleDiverged(
                    f"schedule step {self.i} wants {c!r}; enabled: "
                    f"{list(options)}"
                )
        else:
            if self.stop_after:
                # truncated run: finish a paused step, stop the loop
                return CONTINUE if at_interleave else None
            if self.rng is not None:
                c = options[self.rng.randrange(len(options))]
            else:
                c = options[0]
        self.i += 1
        return c


# ------------------------------------------------------------------ world


class World:
    """One fresh control-plane universe: the real GcsServer under a
    virtual runtime + simulated peers + the step queue."""

    def __init__(self, chooser: Chooser, tracer: BufTracer,
                 step_limit: int = 600):
        self.chooser = chooser
        self.tracer = tracer
        self.step_limit = step_limit
        self.clock = VirtualClock()
        self.steps: List[Step] = []
        self.executed: Set[str] = set()  # labels, for `after` gating
        self.schedule: List[str] = []  # chosen label at every choice point
        self.options_at: List[Tuple[str, ...]] = []
        self.keys_of: Dict[str, FrozenSet[str]] = {}
        self._label_counts: Dict[str, int] = {}
        self._next_conn_id = 10_000
        self._sched_pending = False
        self.crashes: List[str] = []
        self.server: Optional[VirtualServer] = None
        self.gcs = None
        self.daemons: Dict[str, "SimDaemon"] = {}
        self.drivers: Dict[str, "SimDriver"] = {}
        self.stopped_early = False

    # -------------------------------------------------------- lifecycle

    def build_gcs(self, config_overrides: Optional[dict] = None) -> None:
        from ray_tpu.core.config import Config
        from ray_tpu.cluster.gcs import GcsServer

        overrides = {"task_events_spill": False}
        overrides.update(config_overrides or {})
        self.gcs = GcsServer(
            config=Config(overrides), runtime=VirtualRuntime(self)
        )
        # the 2PC gap between prepare and commit is an interleave point:
        # node deaths and rival handlers can land between the phases
        self.gcs._pg_fault_hook = lambda pg_id: self.interleave()

    def close(self) -> None:
        if self.gcs is not None:
            try:
                self.gcs.shutdown()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    # ------------------------------------------------------------ queue

    def enqueue(self, base_label: str, fn: Callable[[], None],
                keys: Sequence[str], after: Optional[str] = None) -> str:
        n = self._label_counts.get(base_label, 0)
        self._label_counts[base_label] = n + 1
        label = base_label if n == 0 else f"{base_label}#{n}"
        self.steps.append(Step(label, fn, frozenset(keys), after))
        self.keys_of[label] = frozenset(keys)
        return label

    def kick(self) -> None:
        if not self._sched_pending:
            self._sched_pending = True
            self.enqueue("sched", self._run_sched, keys={GLOBAL_KEY})

    def _run_sched(self) -> None:
        self._sched_pending = False
        self.gcs._schedule_round()

    def crash(self, where: str, exc: Exception) -> None:
        self.crashes.append(f"{where}: {type(exc).__name__}: {exc}")

    def deliver_push(self, conn: VirtualConn, channel: str,
                     data: Any) -> None:
        peer = conn.peer
        if peer is None:
            return
        if channel in peer.sync_channels:
            # record-only reaction: no GCS state effect, so making it a
            # step would only inflate the schedule space
            peer.on_push(channel, data)
            return
        if channel not in peer.reactive_channels:
            return
        def deliver(p=peer, ch=channel, d=data):
            self.tracer.on_push("gcs", p.name, ch)
            p.on_push(ch, d)
        self.enqueue(
            f"push:{channel}->{peer.name}", deliver,
            keys=peer.push_keys(channel, data),
        )

    def rpc(self, peer: "SimPeer", method: str, params: dict,
            keys: Sequence[str], base_label: Optional[str] = None,
            after: Optional[str] = None,
            conn: Optional[VirtualConn] = None) -> str:
        """Enqueue a peer->GCS RPC delivery step (send is traced when the
        frame 'leaves' = step creation; recv + dispatch at execution)."""
        use_conn = conn or peer.conn

        def fire():
            lc = self.tracer.on_send(peer.name, "gcs", method)
            self.tracer.on_recv(peer.name, "gcs", method, lc)
            try:
                res = self.gcs._handle(method, params, use_conn)
            except Exception as e:  # noqa: BLE001 - a crash IS a finding
                self.crash(f"rpc_{method}", e)
                return
            peer.on_response(method, params, res)

        return self.enqueue(
            base_label or f"rpc:{method}:{peer.name}", fire,
            keys=keys, after=after,
        )

    # -------------------------------------------------------- execution

    def _enabled(self) -> List[Step]:
        return [
            s for s in self.steps
            if s.after is None or s.after in self.executed
        ]

    def run(self) -> None:
        while self.steps:
            if len(self.schedule) >= self.step_limit:
                self.crashes.append(
                    f"step budget exceeded ({self.step_limit}): the "
                    "scenario does not quiesce"
                )
                return
            enabled = self._enabled()
            if not enabled:
                self.crashes.append(
                    "deadlock: pending steps exist but none are enabled "
                    f"({[s.label for s in self.steps]})"
                )
                return
            options = tuple(s.label for s in enabled)
            chosen = self.chooser.choose(options, at_interleave=False)
            if chosen is None:
                self.stopped_early = True
                return
            self._fire(chosen, options)

    def interleave(self) -> None:
        """Choice point inside a running step (the PG 2PC phase gap):
        zero or more enabled steps may run before the step resumes."""
        while True:
            enabled = self._enabled()
            options = (CONTINUE,) + tuple(s.label for s in enabled)
            chosen = self.chooser.choose(options, at_interleave=True)
            if chosen is None or chosen == CONTINUE:
                self.schedule.append(CONTINUE)
                self.options_at.append(options)
                return
            self._fire(chosen, options)

    def _fire(self, label: str, options: Tuple[str, ...]) -> None:
        self.schedule.append(label)
        self.options_at.append(options)
        for i, s in enumerate(self.steps):
            if s.label == label:
                step = self.steps.pop(i)
                break
        else:  # pragma: no cover - choose() only offers pending labels
            raise ScheduleDiverged(f"step {label!r} vanished")
        self.executed.add(label)
        self.clock.advance(0.001)
        step.fn()


# ------------------------------------------------------------- sim peers


class SimPeer:
    #: push channels delivered as schedulable steps
    reactive_channels: FrozenSet[str] = frozenset()
    #: push channels recorded synchronously (no GCS state effect)
    sync_channels: FrozenSet[str] = frozenset()

    def __init__(self, world: World, name: str):
        self.world = world
        self.name = name
        self.conn = VirtualConn(self)
        world.server.conns[self.conn.conn_id] = self.conn
        self.pushed: List[Tuple[str, Any]] = []
        self.responses: List[Tuple[str, Any]] = []

    def new_conn(self) -> VirtualConn:
        self.conn = VirtualConn(self)
        self.world.server.conns[self.conn.conn_id] = self.conn
        return self.conn

    def on_push(self, channel: str, data: Any) -> None:
        self.pushed.append((channel, data))

    def on_response(self, method: str, params: dict, res: Any) -> None:
        self.responses.append((method, res))

    def push_keys(self, channel: str, data: Any) -> Set[str]:
        return {GLOBAL_KEY}


class _SimDaemonClient:
    """The GCS's request/response client to a simulated daemon (2PC
    prepare/commit, stream acks): dispatches synchronously — the 2PC
    *phase gap* is the interleave point, not the individual ack."""

    def __init__(self, daemon: "SimDaemon"):
        self.daemon = daemon

    @property
    def _closed(self) -> bool:
        return not self.daemon.alive

    def call_async(self, method: str, params: dict):
        try:
            return _VirtualFuture(self.daemon.handle_rpc(method, params))
        except Exception as e:  # noqa: BLE001 - mirrors a remote error
            return _VirtualFuture(exc=e)

    def notify(self, method: str, params: dict) -> None:
        self.daemon.handle_rpc(method, params)

    def close(self) -> None:
        pass


class SimDaemon(SimPeer):
    """Protocol-faithful daemon peer: registers with an instance stamp,
    executes dispatched tasks (obj_put trace + task_done report), mirrors
    the 2PC bundle table with the same pg_prepare/pg_commit/pg_return
    trace events the real node_daemon emits, and hosts actor execs."""

    reactive_channels = frozenset(
        {"exec_tasks", "return_bundle", "kill_actor", "free_objects",
         "dag_teardown"}
    )
    sync_channels = frozenset({"nodes"})

    def __init__(self, world: World, node_id: str, cpus: float = 2.0,
                 resend_reports: bool = False):
        super().__init__(world, node_id)
        self.node_id = node_id
        self.cpus = cpus
        self.alive = False
        self.instance = 0
        self.resend_reports = resend_reports
        self._bundles: Dict[str, dict] = {}
        self.store: Set[str] = set()
        self.ran: List[str] = []
        self.exec_seq: Dict[str, int] = {}  # actor -> last executed seq
        self.worker_id = f"{node_id}-w1"
        self.client = _SimDaemonClient(self)
        world.daemons[node_id] = self

    # ------------------------------------------------------- step seeds

    def step_register(self, new_instance: bool = False,
                      new_conn: bool = False) -> str:
        self.instance += 1
        inst = f"{self.node_id}-i{self.instance}"
        if new_conn or new_instance:
            self.new_conn()
        conn = self.conn

        def also():
            self.alive = True
            if new_instance:
                # a fresh daemon process: the old incarnation's store,
                # bundles, and in-flight work are gone
                self.store.clear()
                self._bundles.clear()
        payload = {
            "node_id": self.node_id, "addr": "127.0.0.1",
            "port": 20000, "resources": {"CPU": self.cpus},
            "instance": inst, "labels": {},
        }
        label = self.world.rpc(
            self, "register_node", payload, keys={GLOBAL_KEY},
            base_label=f"reg:{self.node_id}/i{self.instance}", conn=conn,
        )
        # run the local bookkeeping with the registration delivery
        step = next(s for s in self.world.steps if s.label == label)
        orig = step.fn

        def fn():
            also()
            orig()
        step.fn = fn
        return label

    def step_drop_conn(self, conn: Optional[VirtualConn] = None) -> str:
        """The (possibly stale) server-side disconnect of one of this
        daemon's connections."""
        target = conn or self.conn

        def fire():
            target.closed = True
            self.world.server.conns.pop(target.conn_id, None)
            try:
                self.world.gcs._on_disconnect(target)
            except Exception as e:  # noqa: BLE001
                self.world.crash("on_disconnect", e)
        return self.world.enqueue(
            f"drop-conn:{self.node_id}/c{target.conn_id}", fire,
            keys={GLOBAL_KEY},
        )

    def step_kill(self) -> str:
        """Daemon process death: local liveness off + its connection
        drops (the edge-triggered death path)."""
        conn = self.conn

        def fire():
            self.alive = False
            conn.closed = True
            self.world.server.conns.pop(conn.conn_id, None)
            try:
                self.world.gcs._on_disconnect(conn)
            except Exception as e:  # noqa: BLE001
                self.world.crash("on_disconnect", e)
        return self.world.enqueue(
            f"kill:{self.node_id}", fire, keys={GLOBAL_KEY}
        )

    # ----------------------------------------------------- push effects

    def push_keys(self, channel: str, data: Any) -> Set[str]:
        if channel == "exec_tasks":
            return {f"node:{self.node_id}", *(
                f"task:{t['task_id']}" for t in data
            )}
        if channel == "return_bundle":
            return {f"node:{self.node_id}", f"pg:{data['pg_id']}"}
        return {GLOBAL_KEY}

    def on_push(self, channel: str, data: Any) -> None:
        super().on_push(channel, data)
        if channel == "exec_tasks":
            inst = self.instance
            for t in data:
                self.world.enqueue(
                    f"run:{t['task_id']}@{self.node_id}",
                    lambda t=t, i=inst: self._run_task(t, i),
                    keys={f"task:{t['task_id']}", f"node:{self.node_id}"},
                )
        elif channel == "return_bundle":
            key = f"{data['pg_id']}:{data['bundle_index']}"
            if self._bundles.pop(key, None) is not None:
                self.world.tracer.apply(
                    "pg_return", pg=data["pg_id"],
                    bundle=data["bundle_index"], node=self.node_id,
                )
        elif channel == "free_objects":
            self.store -= set(data["object_ids"])

    def _run_task(self, t: dict, instance: int) -> None:
        if not self.alive or instance != self.instance:
            return  # the incarnation that was asked to run this is gone
        from ray_tpu.core.object_ref import ObjectRef

        tid = t["task_id"]
        self.ran.append(tid)
        results = []
        for i in range(int(t.get("num_returns", 1) or 1)):
            oid = ObjectRef.for_task_output(tid, i).id
            self.store.add(oid)
            self.world.tracer.apply("obj_put", oid=oid, node=self.node_id)
            results.append((oid, 8))
        payload = {
            "task_id": tid, "node_id": self.node_id, "status": "FINISHED",
            "results": results, "name": t.get("name") or "sim",
            "start": self.world.clock.now(),
            "end": self.world.clock.now(),
        }
        if t.get("actor_creation"):
            payload["actor_creation"] = True
            payload["actor_id"] = t.get("actor_id")
        keys = {
            f"task:{tid}", f"cap:{self.node_id}",
            *(f"obj:{oid}" for oid, _ in results),
        }
        if t.get("actor_creation"):
            keys.add(GLOBAL_KEY)  # actor table + hold retag ripple wider
        sends = 2 if self.resend_reports else 1
        for _ in range(sends):
            self.world.rpc(
                self, "task_done", payload, keys=keys,
                base_label=f"done:{tid}@{self.node_id}",
            )

    # --------------------------------------------- gcs-initiated rpcs

    def handle_rpc(self, method: str, params: dict):
        if not self.alive:
            raise ConnectionError(f"daemon {self.node_id} is down")
        if method == "prepare_bundle":
            self.world.tracer.apply(
                "pg_prepare", pg=params["pg_id"],
                bundle=params["bundle_index"], node=self.node_id, ok=True,
            )
            key = f"{params['pg_id']}:{params['bundle_index']}"
            self._bundles[key] = {**params, "state": "PREPARED"}
            return {"ok": True}
        if method == "commit_bundle":
            key = f"{params['pg_id']}:{params['bundle_index']}"
            ent = self._bundles.get(key)
            ok = ent is not None
            self.world.tracer.apply(
                "pg_commit", pg=params["pg_id"],
                bundle=params["bundle_index"], node=self.node_id, ok=ok,
                transition=ok and ent.get("state") != "COMMITTED",
            )
            if not ok:
                return {"ok": False, "error": "no prepared bundle"}
            ent["state"] = "COMMITTED"
            return {"ok": True}
        if method == "stream_ack":
            return {"ok": True}
        raise ValueError(f"sim daemon has no rpc {method}")

    # ------------------------------------------------------ actor execs

    def exec_actor_call(self, owner: str, actor: str, seq: int) -> None:
        self.exec_seq[actor] = seq
        self.world.tracer.apply(
            "actor_exec", owner=owner, actor=actor,
            worker=self.worker_id, seq=seq,
        )

    def step_worker_restart(self, actor: str) -> str:
        """The worker hosting ``actor`` dies and restarts. Calls still
        pending at the restart execute on the NEW incarnation
        (exec_actor_call reads ``worker_id`` live) — the fixed client
        protocol's replay semantics: a fresh worker key restarts the
        per-worker seq ordering the invariant checker tracks."""

        def fire():
            n = int(self.worker_id.rsplit("w", 1)[1]) + 1
            self.worker_id = f"{self.node_id}-w{n}"
        return self.world.enqueue(
            f"wrestart:{self.node_id}", fire,
            keys={f"actor:{actor}", f"node:{self.node_id}"},
        )


class SimDriver(SimPeer):
    sync_channels = frozenset(
        {"task_result", "nodes", "actor_update", "dag_update",
         "borrow_added", "borrow_released", "stream_item"}
    )

    def __init__(self, world: World, driver_id: str):
        super().__init__(world, driver_id)
        self.driver_id = driver_id
        self.results: Dict[str, Any] = {}

    def on_push(self, channel: str, data: Any) -> None:
        super().on_push(channel, data)
        if channel == "task_result":
            self.results[data.get("task_id")] = data.get("status")

    def step_register(self) -> str:
        return self.world.rpc(
            self, "register_driver", {"driver_id": self.driver_id},
            keys={GLOBAL_KEY}, base_label=f"reg-driver:{self.driver_id}",
        )

    def task_meta(self, task_id: str, cpus: float = 1.0,
                  **extra) -> dict:
        meta = {
            "task_id": task_id, "name": task_id,
            "class_key": ("sim", (("CPU", float(cpus)),)),
            "resources": {"CPU": float(cpus)},
            "owner": self.driver_id, "num_returns": 1,
        }
        meta.update(extra)
        return meta

    def step_submit(self, meta: dict) -> str:
        # submissions conflict with each other (and scheduler rounds)
        # through the intake queue's order — under scarce capacity,
        # which of two tasks dispatches first is semantically different
        return self.world.rpc(
            self, "submit_task", meta,
            keys={f"task:{meta['task_id']}", "sched-queue"},
            base_label=f"sub:{meta['task_id']}",
        )

    def step_free(self, oids: List[str], tag: str) -> str:
        return self.world.rpc(
            self, "free_objects", {"object_ids": oids},
            keys={f"obj:{o}" for o in oids}, base_label=f"free:{tag}",
        )

    def step_disconnect(self) -> str:
        conn = self.conn

        def fire():
            conn.closed = True
            self.world.server.conns.pop(conn.conn_id, None)
            try:
                self.world.gcs._on_disconnect(conn)
            except Exception as e:  # noqa: BLE001
                self.world.crash("on_disconnect", e)
        return self.world.enqueue(
            f"disc:{self.driver_id}", fire, keys={GLOBAL_KEY}
        )


# -------------------------------------------------------------- scenarios


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    build: Callable[[World], None]
    #: quiescence-only assertions returning violation strings
    postcheck: Optional[Callable[[World], List[str]]] = None


def _no_leaked_holds(world: World) -> List[str]:
    """A lifetime hold is LEAKED when its owner can no longer release
    it: an actor-hold whose actor is DEAD/unknown, a dag-hold whose dag
    is unregistered. ALIVE actors and live dags legally hold capacity."""
    out = []
    for key in world.gcs.running:
        if key.startswith("actor-hold-"):
            a = world.gcs.actors.get(key[len("actor-hold-"):])
            if a is None or a.get("state") == "DEAD":
                out.append(f"hold {key} leaked at quiescence "
                           f"(actor state: {a and a.get('state')})")
        elif key.startswith("dag-hold-"):
            dag_id = key[len("dag-hold-"):].rsplit("-", 1)[0]
            if dag_id not in world.gcs.dags:
                out.append(f"hold {key} leaked at quiescence "
                           "(dag unregistered)")
    return out


def _build_node_reconnect(world: World) -> None:
    d0 = SimDaemon(world, "d0", cpus=2.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    first_conn = d0.conn
    d0.step_register()
    drv.step_submit(drv.task_meta("t1", cpus=2.0))
    d0.step_register(new_instance=True)  # restart with a fresh stamp
    d0.step_drop_conn(first_conn)  # the old conn's disconnect lands late
    drv.step_submit(drv.task_meta("t2", cpus=2.0))
    drv.step_submit(drv.task_meta("t3", cpus=2.0))


def _post_node_reconnect(world: World) -> List[str]:
    out = _no_leaked_holds(world)
    d0 = world.daemons["d0"]
    n = world.gcs.nodes.get("d0")
    if d0.alive and n is not None and not n.get("alive") and \
            n.get("conn_id") == d0.conn.conn_id:
        out.append(
            "node d0 marked dead while its latest registration's "
            "connection is still open (a stale conn's disconnect killed "
            "the re-registered node)"
        )
    return out


def _build_watchdog_resend(world: World) -> None:
    from ray_tpu.core.object_ref import ObjectRef

    d0 = SimDaemon(world, "d0", cpus=2.0, resend_reports=True)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    drv.step_submit(drv.task_meta("t1"))
    drv.step_submit(drv.task_meta("t1"))  # duplicate submission
    drv.step_submit(drv.task_meta("t2"))
    oid = ObjectRef.for_task_output("t1", 0).id
    drv.step_free([oid], tag="t1-out")


def _post_watchdog_resend(world: World) -> List[str]:
    # NOTE: a duplicate submission MAY legally re-execute after the
    # first execution completed (lineage reconstruction re-runs finished
    # producers); the real contract — never two dispatches outstanding
    # at once — is the exactly-once trace invariant, checked per run
    return _no_leaked_holds(world)


def _build_pg_vs_death(world: World) -> None:
    d0 = SimDaemon(world, "d0", cpus=1.0)
    d1 = SimDaemon(world, "d1", cpus=1.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    d1.step_register()
    world.rpc(
        drv, "create_placement_group",
        {"pg_id": "p1", "bundles": [{"CPU": 1.0}, {"CPU": 1.0}],
         "strategy": "PACK"},
        keys={GLOBAL_KEY}, base_label="pg:create:p1",
    )
    d1.step_kill()
    world.rpc(
        drv, "remove_placement_group", {"pg_id": "p1"},
        keys={GLOBAL_KEY}, base_label="pg:remove:p1",
    )


def _build_dag_vs_disconnect(world: World) -> None:
    d0 = SimDaemon(world, "d0", cpus=2.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    world.rpc(
        drv, "dag_register",
        {"dag_id": "g1", "owner": "drv0",
         "stages": [
             {"stage": 0, "resources": {"CPU": 1.0}},
             {"stage": 1, "resources": {"CPU": 1.0}},
         ]},
        keys={GLOBAL_KEY}, base_label="dag:reg:g1",
    )
    world.rpc(
        drv, "dag_teardown", {"dag_id": "g1"},
        keys={GLOBAL_KEY}, base_label="dag:teardown:g1",
    )
    drv.step_disconnect()


def _build_actor_kill_vs_create(world: World) -> None:
    d0 = SimDaemon(world, "d0", cpus=2.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    reg = world.rpc(
        drv, "register_actor",
        {"actor_id": "a1", "class_name": "Sim", "max_restarts": 0},
        keys={"actor:a1"}, base_label="actor:reg:a1",
    )
    sub = drv.step_submit(drv.task_meta(
        "c1", cpus=1.0, actor_creation=True, actor_id="a1",
    ))
    # kill/died causally follow the registration (a handle — and a
    # hosted worker — exist only after it); any later interleaving is
    # fair game
    world.rpc(
        drv, "kill_actor", {"actor_id": "a1"},
        keys={"actor:a1", GLOBAL_KEY}, base_label="actor:kill:a1",
        after=reg,
    )
    world.rpc(
        d0, "actor_died", {"actor_id": "a1", "cause": "worker died"},
        keys={"actor:a1", GLOBAL_KEY}, base_label="actor:died:a1",
        after=sub,
    )


def _build_actor_kill_vs_release(world: World) -> None:
    """Hunt for the ISSUE-14 soak transient: a ``[capacity]`` actor-hold
    release-node-mismatch ("release of 'actor-hold-a1' on dX but the
    allocation lives on dY"). The window under test is the death of the
    hold's HOME NODE between the creation's dispatch debit and the
    kill/died release credit: a restartable actor re-creates on the
    surviving daemon, so the hold's home flips mid-race and every release
    path (kill_actor, actor_died, node sweep) must credit where the
    allocation LIVES, never where it first landed.

    Clean sweep recorded 2026-08-07: 1400 DFS + 800 sampled schedules,
    0 violations (capacity conservation, exactly-once, no leaked holds),
    40 handler-pair orderings covered — the PR 14 transient did not
    reproduce under this model; if it resurfaces in a soak, replay its
    trace against this scenario's postcheck first."""
    d0 = SimDaemon(world, "d0", cpus=1.0)
    d1 = SimDaemon(world, "d1", cpus=1.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    d1.step_register()
    reg = world.rpc(
        drv, "register_actor",
        {"actor_id": "a1", "class_name": "Sim", "max_restarts": 1},
        keys={"actor:a1"}, base_label="actor:reg:a1",
    )
    sub = drv.step_submit(drv.task_meta(
        "c1", cpus=1.0, actor_creation=True, actor_id="a1",
    ))
    # the home-node kill can land before dispatch, between debit and
    # task_done's actor-hold retag, or after the hold settled — the
    # restart then re-places the actor on d1
    d0.step_kill()
    world.rpc(
        drv, "kill_actor", {"actor_id": "a1"},
        keys={"actor:a1", GLOBAL_KEY}, base_label="actor:kill:a1",
        after=reg,
    )
    # a (possibly stale) died report from the SURVIVING daemon: after a
    # restart relocated the actor, this is the release path whose node
    # attribution the mismatch message complained about
    world.rpc(
        d1, "actor_died", {"actor_id": "a1", "cause": "worker died"},
        keys={"actor:a1", GLOBAL_KEY}, base_label="actor:died:a1",
        after=sub,
    )


def _build_actor_replay(world: World) -> None:
    d0 = SimDaemon(world, "d0", cpus=2.0)
    drv = SimDriver(world, "drv0")
    drv.step_register()
    d0.step_register()
    # per-connection FIFO: seq 2's delivery is gated on seq 1's (the
    # client's ordered-submission pipeline); the worker restart replays
    # only calls the dead incarnation had not executed
    l1 = world.enqueue(
        "acall:a1/s1", lambda: d0.exec_actor_call("drv0", "a1", 1),
        keys={"actor:a1"},
    )
    world.enqueue(
        "acall:a1/s2", lambda: d0.exec_actor_call("drv0", "a1", 2),
        keys={"actor:a1"}, after=l1,
    )
    d0.step_worker_restart("a1")


SCENARIOS: Dict[str, Scenario] = {
    s.name: s for s in [
        Scenario(
            "node-reconnect-instance",
            "daemon restart with a fresh instance stamp racing task "
            "dispatch/completion and the old connection's late disconnect",
            _build_node_reconnect, _post_node_reconnect,
        ),
        Scenario(
            "watchdog-resend",
            "duplicated task submission + watchdog-resent task_done "
            "reports racing dispatch and an owner free",
            _build_watchdog_resend, _post_watchdog_resend,
        ),
        Scenario(
            "pg-2pc-vs-node-death",
            "placement-group 2PC prepare/commit with a member node dying "
            "at every point, including between the phases, and a "
            "concurrent remove",
            _build_pg_vs_death, _no_leaked_holds,
        ),
        Scenario(
            "dag-register-vs-driver-disconnect",
            "compiled-dag registration racing its owner's disconnect "
            "sweep and an explicit teardown",
            _build_dag_vs_disconnect, _no_leaked_holds,
        ),
        Scenario(
            "actor-kill-vs-create",
            "actor creation in flight racing ray.kill and a daemon "
            "actor_died report (lifetime-hold conservation)",
            _build_actor_kill_vs_create, _no_leaked_holds,
        ),
        Scenario(
            "actor-kill-vs-release",
            "restartable actor whose home node dies between the creation "
            "dispatch debit and the kill/died release credit: the hold "
            "relocates with the restart, hunting the PR 14 "
            "release-node-mismatch transient",
            _build_actor_kill_vs_release, _no_leaked_holds,
        ),
        Scenario(
            "actor-replay",
            "ordered actor calls with a worker restart replaying "
            "in-flight calls on the new incarnation",
            _build_actor_replay, None,
        ),
    ]
}


# ---------------------------------------------------------------- results


@dataclasses.dataclass
class WorldResult:
    scenario: str
    schedule: List[str]
    options_at: List[Tuple[str, ...]]
    keys_of: Dict[str, FrozenSet[str]]
    violations: List[Violation]
    events: List[Dict[str, Any]]
    quiesced: bool

    @property
    def violation_kinds(self) -> Set[str]:
        return {v.kind for v in self.violations}

    def schedule_log(self) -> str:
        return " | ".join(self.schedule)


def run_world(scenario: Scenario, chooser: Chooser,
              seeded_bugs: Sequence[str] = (),
              step_limit: int = 600) -> WorldResult:
    """Execute one schedule of ``scenario`` from a fresh world; returns
    the schedule actually taken plus every violation (invariants over the
    trace, handler crashes, unmet postconditions)."""
    from ray_tpu.cluster import gcs as gcs_mod
    from ray_tpu.cluster import rpc as rpc_mod

    prev_trace = rpc_mod.TRACE
    prev_bugs = set(gcs_mod.SEEDED_BUGS)
    tracer = BufTracer()
    rpc_mod.TRACE = tracer
    gcs_mod.SEEDED_BUGS.clear()
    gcs_mod.SEEDED_BUGS.update(seeded_bugs)
    world = World(chooser, tracer, step_limit=step_limit)
    try:
        world.build_gcs()
        scenario.build(world)
        world.run()
        violations = InvariantChecker().run(list(tracer.records))
        clock = tracer._clock
        for c in world.crashes:
            violations.append(Violation("crash", c, clock))
        quiesced = not world.steps and not world.stopped_early
        if quiesced and scenario.postcheck is not None:
            for msg in scenario.postcheck(world):
                violations.append(Violation("postcheck", msg, clock))
        return WorldResult(
            scenario=scenario.name,
            schedule=list(world.schedule),
            options_at=list(world.options_at),
            keys_of=dict(world.keys_of),
            violations=violations,
            events=list(tracer.records),
            quiesced=quiesced,
        )
    finally:
        world.close()
        rpc_mod.TRACE = prev_trace
        gcs_mod.SEEDED_BUGS.clear()
        gcs_mod.SEEDED_BUGS.update(prev_bugs)


@dataclasses.dataclass
class ExploreResult:
    scenario: str
    schedules_run: int
    dfs_schedules: int
    sampled_schedules: int
    branches_pruned: int
    branches_queued: int
    coverage: Set[Tuple[str, str]]
    elapsed_s: float
    violating: Optional[WorldResult] = None
    shrunk: Optional[List[str]] = None
    shrunk_violations: Optional[List[Violation]] = None
    shrunk_stop_after: bool = True

    @property
    def found(self) -> bool:
        return self.violating is not None

    def summary(self) -> str:
        head = (
            f"{self.scenario}: {self.schedules_run} schedules "
            f"({self.dfs_schedules} dfs + {self.sampled_schedules} "
            f"sampled), {self.branches_pruned} branches pruned, "
            f"{len(self.coverage)} handler-pair orderings, "
            f"{self.elapsed_s:.2f}s"
        )
        if not self.found:
            return head + " — no violations"
        kinds = sorted({v.kind for v in self.violating.violations})
        n = len(self.shrunk or self.violating.schedule)
        return head + f" — VIOLATION {kinds}, shrunk to {n} steps"


# ----------------------------------------------------- generic engine
#
# The DFS + conflict-pruning + shrink machinery below is deliberately
# generic over a duck-typed *run result* (needs: ``schedule``,
# ``options_at``, ``keys_of``, ``violations`` with ``.kind``,
# ``violation_kinds``) so other model checkers — analysis/memmodel.py's
# word-level channel checker — reuse the exact same engine instead of
# re-implementing (and diverging on) persistent-set pruning and
# delta-debug shrinking. ``explore()``/``shrink_schedule()`` are the
# GCS-scenario instantiations.


def _conflicts(a: FrozenSet[str], b: FrozenSet[str]) -> bool:
    return GLOBAL_KEY in a or GLOBAL_KEY in b or bool(a & b)


def _backtrack_alternatives(
    res, start: int, max_depth: Optional[int],
    conflicts: Callable[[FrozenSet[str], FrozenSet[str]], bool] = _conflicts,
    process_of: Optional[Callable[[str], str]] = None,
) -> List[Tuple[int, str]]:
    """(position, alternative) pairs worth branching on, persistent-set
    style: an unchosen enabled step is explored at position i only when
    something that ran in [i, its own turn) conflicts with it.

    With ``process_of`` (worlds whose steps are *per-process program
    counters*, e.g. memmodel's actor ops), the Flanagan–Godefroid
    refinement also branches an alternative whose own op commutes but
    whose process has a LATER op conflicting with something that ran in
    between — without it, a benign leading op (a load of an untouched
    word) shields its process's entire remaining schedule from DFS."""
    out: List[Tuple[int, str]] = []
    sched = res.schedule
    limit = len(sched) if max_depth is None else min(len(sched), max_depth)
    pos_of = {label: i for i, label in enumerate(sched)}
    later_union: List[Dict[str, FrozenSet[str]]] = []
    if process_of is not None:
        # later_union[x][P]: union of key footprints of P's steps after
        # position x — one backwards sweep, queried per (between, proc)
        acc: Dict[str, FrozenSet[str]] = {}
        rev: List[Dict[str, FrozenSet[str]]] = []
        for label in reversed(sched):
            rev.append(dict(acc))
            if label != CONTINUE:
                p = process_of(label)
                acc[p] = acc.get(p, frozenset()) | \
                    res.keys_of.get(label, frozenset({GLOBAL_KEY}))
        later_union = list(reversed(rev))
    for i in range(start, limit):
        chosen = sched[i]
        for alt in res.options_at[i]:
            if alt == chosen or alt == CONTINUE:
                continue
            akeys = res.keys_of.get(alt, frozenset({GLOBAL_KEY}))
            j = pos_of.get(alt)
            if j is None:
                out.append((i, alt))  # never ran (truncation): explore
                continue
            aproc = process_of(alt) if process_of is not None else None
            branch = False
            for x_i in range(i, j):
                x = sched[x_i]
                if x == CONTINUE:
                    continue
                xkeys = res.keys_of.get(x, frozenset({GLOBAL_KEY}))
                if conflicts(akeys, xkeys):
                    branch = True
                    break
                if aproc is not None and \
                        process_of(x) != aproc and conflicts(
                            xkeys, later_union[x_i].get(aproc, frozenset())
                        ):
                    branch = True
                    break
            if branch:
                out.append((i, alt))
    return out


@dataclasses.dataclass
class EngineStats:
    """What the generic DFS+sampling engine hands back to its caller."""

    violating: Optional[Any]
    dfs_runs: int
    sampled_runs: int
    pruned: int
    queued: int


def dfs_explore(
    run_fn: Callable[[Chooser], Any],
    *,
    max_schedules: int,
    max_depth: Optional[int],
    samples: int,
    seed: int,
    wall_cap_s: Optional[float] = None,
    conflicts: Callable[[FrozenSet[str], FrozenSet[str]], bool] = _conflicts,
    process_of: Optional[Callable[[str], str]] = None,
    on_result: Optional[Callable[[Any], None]] = None,
) -> EngineStats:
    """Generic exploration loop: bounded-depth DFS with persistent-set
    pruning over ``run_fn``'s schedules, then seeded-random sampling.
    ``run_fn(chooser)`` executes ONE schedule from a fresh world and
    returns the duck-typed run result; it may raise ScheduleDiverged.
    Stops at the first violating result."""
    import random

    t0 = _time.monotonic()
    frontier: List[Tuple[str, ...]] = [()]
    seen: Set[Tuple[str, ...]] = {()}
    dfs_runs = 0
    sampled_runs = 0
    pruned = 0
    queued = 0
    violating = None

    def out_of_wall() -> bool:
        return (
            wall_cap_s is not None and _time.monotonic() - t0 > wall_cap_s
        )

    def out_of_budget() -> bool:
        # max_schedules bounds the DFS half; the sampling half has its
        # own ``samples`` budget (a DFS that fills its budget must not
        # starve the random pass — the two find different bugs)
        return out_of_wall() or dfs_runs >= max_schedules

    while frontier and not out_of_budget() and violating is None:
        prefix = frontier.pop()
        try:
            res = run_fn(Chooser(prefix))
        except ScheduleDiverged:  # pragma: no cover - determinism guard
            continue
        dfs_runs += 1
        if on_result is not None:
            on_result(res)
        if res.violations:
            violating = res
            break
        alts = _backtrack_alternatives(res, len(prefix), max_depth,
                                       conflicts=conflicts,
                                       process_of=process_of)
        total_alts = 0
        for i, alt in reversed(alts):
            total_alts += 1
            new_prefix = tuple(res.schedule[:i]) + (alt,)
            if new_prefix in seen:
                continue
            seen.add(new_prefix)
            frontier.append(new_prefix)
            queued += 1
        # pruning accounting: enabled-but-not-branched alternatives
        limit = (
            len(res.schedule) if max_depth is None
            else min(len(res.schedule), max_depth)
        )
        enabled_alts = sum(
            len([o for o in res.options_at[i]
                 if o not in (res.schedule[i], CONTINUE)])
            for i in range(len(prefix), limit)
        )
        pruned += max(0, enabled_alts - total_alts)

    rng_base = random.Random(seed)
    while (
        violating is None and sampled_runs < samples and not out_of_wall()
    ):
        rng = random.Random(rng_base.getrandbits(64))
        try:
            res = run_fn(Chooser(rng=rng))
        except ScheduleDiverged:  # pragma: no cover
            continue
        sampled_runs += 1
        if on_result is not None:
            on_result(res)
        if res.violations:
            violating = res

    return EngineStats(
        violating=violating,
        dfs_runs=dfs_runs,
        sampled_runs=sampled_runs,
        pruned=pruned,
        queued=queued,
    )


def shrink_generic(
    run_fn: Callable[[Chooser], Any],
    schedule: List[str],
    target_kinds: Set[str],
    stop_after: bool,
    max_attempts: int = 400,
    chooser_factory: Optional[
        Callable[[Sequence[str], bool], Chooser]
    ] = None,
    blocks_of: Optional[Callable[[List[str]], List[Tuple[int, int]]]] = None,
) -> Tuple[List[str], List[Violation]]:
    """Minimize a violating schedule: greedy prefix truncation, then
    single-step delta removal — plus, when ``blocks_of`` is given,
    contiguous-block removal (a world whose step labels carry per-actor
    op counters renumbers every later label when one op is dropped, so
    only whole blocks — e.g. a spin-wait iteration — can go; pair with a
    counter-insensitive ``chooser_factory``). Every candidate is
    re-executed from scratch via ``run_fn``; a candidate survives only
    if it still produces a violation of one of the original kinds."""
    if chooser_factory is None:
        chooser_factory = lambda prefix, stop: Chooser(  # noqa: E731
            prefix, stop_after=stop
        )

    def still_bad(cand: List[str]) -> Optional[List[Violation]]:
        try:
            r = run_fn(chooser_factory(cand, stop_after))
        except ScheduleDiverged:
            return None
        if r.violation_kinds & target_kinds:
            return r.violations
        return None

    attempts = 0
    current = list(schedule)
    best_viol = still_bad(current)
    if best_viol is None:  # pragma: no cover - caller passes a violator
        return current, []
    if stop_after:
        # truncate: shortest prefix that still violates
        lo, hi = 0, len(current)
        while lo < hi and attempts < max_attempts:
            mid = (lo + hi) // 2
            attempts += 1
            v = still_bad(current[:mid])
            if v is not None:
                hi = mid
                best_viol = v
            else:
                lo = mid + 1
        current = current[:hi]
    changed = True
    while changed and attempts < max_attempts:
        changed = False
        if blocks_of is not None:
            # contiguous-block removals first (largest wins fastest)
            for s, e in sorted(blocks_of(current),
                               key=lambda b: b[0] - b[1]):
                if attempts >= max_attempts:
                    break
                cand = current[:s] + current[e:]
                attempts += 1
                v = still_bad(cand)
                if v is not None:
                    current = cand
                    best_viol = v
                    changed = True
                    break
            if changed:
                continue
        # downward single-step removals: dropping index i leaves the
        # positions below it valid, so one pass is index-stable
        i = len(current) - 1
        while i >= 0 and attempts < max_attempts:
            cand = current[:i] + current[i + 1:]
            attempts += 1
            v = still_bad(cand)
            if v is not None:
                current = cand
                best_viol = v
                changed = True
            i -= 1
    return current, best_viol


def shrink_schedule(
    scenario: Scenario, schedule: List[str], target_kinds: Set[str],
    seeded_bugs: Sequence[str], stop_after: bool,
    max_attempts: int = 400,
) -> Tuple[List[str], List[Violation]]:
    """GCS-scenario instantiation of :func:`shrink_generic`."""
    return shrink_generic(
        lambda chooser: run_world(scenario, chooser,
                                  seeded_bugs=seeded_bugs),
        schedule, target_kinds, stop_after, max_attempts=max_attempts,
    )


def explore(
    scenario: Scenario,
    max_schedules: int = 500,
    max_depth: Optional[int] = 30,
    samples: int = 100,
    seed: int = 0,
    seeded_bugs: Sequence[str] = (),
    wall_cap_s: Optional[float] = None,
    shrink: bool = True,
    step_limit: int = 600,
) -> ExploreResult:
    """DFS + random-sampling exploration of one scenario (via the
    generic :func:`dfs_explore` engine). Stops at the first violating
    schedule (shrinking it), or when the schedule budget / wall cap
    runs out."""
    t0 = _time.monotonic()
    coverage: Set[Tuple[str, str]] = set()

    stats = dfs_explore(
        lambda chooser: run_world(
            scenario, chooser, seeded_bugs=seeded_bugs,
            step_limit=step_limit,
        ),
        max_schedules=max_schedules,
        max_depth=max_depth,
        samples=samples,
        seed=seed,
        wall_cap_s=wall_cap_s,
        on_result=lambda res: coverage.update(
            interleaving_coverage(res.events)
        ),
    )
    violating = stats.violating

    result = ExploreResult(
        scenario=scenario.name,
        schedules_run=stats.dfs_runs + stats.sampled_runs,
        dfs_schedules=stats.dfs_runs,
        sampled_schedules=stats.sampled_runs,
        branches_pruned=stats.pruned,
        branches_queued=stats.queued,
        coverage=coverage,
        elapsed_s=_time.monotonic() - t0,
        violating=violating,
    )
    if violating is not None and shrink:
        kinds = violating.violation_kinds
        # postcheck violations only exist at quiescence: shrink those
        # with the default tail instead of truncation
        stop_after = "postcheck" not in kinds
        shrunk, viol = shrink_schedule(
            scenario, violating.schedule, kinds, seeded_bugs, stop_after
        )
        result.shrunk = shrunk
        result.shrunk_violations = viol
        result.shrunk_stop_after = stop_after
    return result


def explore_all(
    names: Optional[Sequence[str]] = None, **kw
) -> Dict[str, ExploreResult]:
    out: Dict[str, ExploreResult] = {}
    for name in names or sorted(SCENARIOS):
        out[name] = explore(SCENARIOS[name], **kw)
    return out


# ----------------------------------------------------------------- replay


def write_replay(path: str, result: ExploreResult,
                 seeded_bugs: Sequence[str] = ()) -> None:
    assert result.violating is not None, "nothing to replay"
    schedule = result.shrunk or result.violating.schedule
    viols = (
        result.shrunk_violations
        if result.shrunk is not None
        else result.violating.violations
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "scenario": result.scenario,
            "seeded_bugs": sorted(seeded_bugs),
            "stop_after": result.shrunk_stop_after,
            "schedule": schedule,
            "violation_kinds": sorted({v.kind for v in (viols or [])}),
            "violations": [v.format() for v in (viols or [])],
        }, f, indent=2)
        f.write("\n")


def replay(path: str) -> WorldResult:
    """Re-execute a recorded counterexample deterministically."""
    with open(path, "r", encoding="utf-8") as f:
        rec = json.load(f)
    scenario = SCENARIOS.get(rec["scenario"])
    if scenario is None:
        raise ValueError(f"unknown scenario {rec['scenario']!r}")
    return run_world(
        scenario,
        Chooser(rec["schedule"], stop_after=rec.get("stop_after", True)),
        seeded_bugs=rec.get("seeded_bugs", ()),
    )
