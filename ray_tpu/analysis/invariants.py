"""Happens-before protocol tracing + offline invariant checking.

The dynamic cross-check of the static protocol model in
:mod:`ray_tpu.analysis.protocol` — the same relationship the runtime
lock-order sanitizer has to the static lock graph. A
:class:`ProtocolTracer` installed via :func:`install` records control-
plane events (frame sends/recvs from hook points in ``cluster/rpc.py``
plus application-level *apply* events from the GCS/daemon/client) to a
JSONL trace, each stamped with a Lamport clock. :func:`check_trace`
replays a trace offline and verifies the protocol invariants the
retry/replay machinery of the reconnecting control plane must preserve:

- **exactly-once**: a ``task_done`` report mutates GCS state at most once
  per (task, execution) — watchdog resends and chaos-duplicated frames
  must be absorbed by the dedupe paths;
- **capacity conservation**: per node, outstanding dispatched demand
  (tasks + staged PG bundles) never exceeds the node total and never goes
  negative — releases match allocations (cf. Narayanan et al.,
  "Heterogeneity-Aware Cluster Scheduling Policies": every guarantee
  presumes the capacity ledger never drifts);
- **PG 2PC legality**: per (node, pg, bundle), commit transitions only
  out of a prepared state; returns/aborts are idempotent;
- **actor ordering**: per (caller, actor, hosting worker) executed
  sequence numbers are strictly increasing;
- **borrow conservation**: borrow releases never exceed registrations
  per (object, worker); optionally, terminal outstanding count is zero;
- **admission conservation** (overload control plane): the GCS's
  ``admit``/``admit_exit`` events — emitted at every queue enter/exit —
  balance per task (an exit without an admit is a ledger bug), and in
  ``strict_terminal`` mode every admitted task must have terminally
  resolved (result, typed failure, or hand-back) by the end of the
  trace: admission control may REJECT loudly, but never drop silently;
- **object lifecycle**: an object location is only ever recorded after a
  store put on that node, and never re-surfaces after a free without an
  intervening re-creation (created -> sealed/put -> located -> freed);
- **channel alternation** (compiled DAGs): per edge, frame seqs written
  by the single writer are gap-free (+1 each), every read consumes the
  next unread seq, and no seq is read before it was written — the shm
  seqlock's write/ack alternation, checked offline. Channel events carry
  their happens-before through the channel header's clock words (see
  ray_tpu/dag/channel.py), since those frames never cross the RPC layer.

Activation mirrors ``ray_tpu.chaos``: a single module-global hook
(``rpc.TRACE``) checked with ``is None`` on the hot path — zero overhead
when no tracer is installed — plus ``RAY_TPU_TRACE_FILE`` env activation
so spawned subprocesses can join the same trace file (append-mode, one
JSON line per event; in-process daemons/GCS/driver are what the
invariants need, so tests normally trace only the test process).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

ENV_TRACE = "RAY_TPU_TRACE_FILE"

#: rpc methods whose apply semantics the invariant checker models; the
#: static protocol dump must know every one of them (see
#: test_dump_protocol_roundtrips_method_table) so the two halves cannot
#: drift apart silently.
METHOD_TABLE: Dict[str, str] = {
    "submit_task": "exactly-once (GCS running-table dedupe)",
    "task_done": "exactly-once + capacity release + object location",
    "register_node": "capacity ledger reset semantics",
    "node_sync": "object location resync",
    "add_object_location": "object lifecycle (located)",
    "free_objects": "object lifecycle (freed)",
    "prepare_bundle": "PG 2PC prepare",
    "commit_bundle": "PG 2PC commit",
    "create_placement_group": "PG capacity stage",
    "remove_placement_group": "PG capacity release",
    "actor_call": "per-caller actor seq monotonicity",
    "register_borrows": "borrow conservation (register)",
    "borrow_released": "borrow conservation (release)",
    "kill_actor": "actor lifetime-hold release",
    "actor_died": "actor lifetime-hold release",
    "stream_item": "object lifecycle (located)",
    # overload control plane: admission enter/exit events pair at every
    # queue transition (admission conservation — every admitted task
    # terminally resolves), drain marks a node unschedulable while its
    # running tasks bleed off
    "drain_node": "node unschedulable marking (graceful drain)",
    # gray-failure defense plane: quarantine is the reversible drain-mask
    # twin (probe-verified recovery instead of terminate); probe results
    # drive the QUARANTINED -> PROBATION exit. Speculative executions ride
    # the dispatch/release ledger under per-copy keys (task~sN) with
    # exactly-one winning task_done apply and cancel-conservation on the
    # losers (see _on_dispatch/_on_spec_cancel/_on_spec_promote)
    "quarantine_node": "reversible node quarantine mask (gray defense)",
    "probe_result": "quarantine recovery probing (gray defense)",
    # compiled DAGs (ray_tpu/dag): stage capacity holds follow the same
    # dispatch/release ledger as tasks; channel frames follow the per-edge
    # seq-alternation invariant (chan_write/chan_read apply events emitted
    # by the exec loops, clocks carried through the shm header)
    "dag_register": "dag stage capacity holds (dispatch)",
    "dag_teardown": "dag stage capacity release + channel teardown",
    "dag_worker_died": "dag broken propagation + stage-hold release",
    "dag_start_stage": "stage worker pinning",
    "dag_push": "channel frame deposit (chan seq alternation)",
    "dag_pull": "channel frame consume (chan seq alternation)",
    # serve fast path (ray_tpu/serve/fastpath.py): pair registration is
    # the plane's only control traffic; request/response frames ride the
    # same per-channel seq-alternation invariant as dag edges
    "serve_register": "fast-path pair registration (placement + sweep)",
    "serve_teardown": "fast-path pair release + channel teardown",
    "serve_attach": "pair channel creation + replica worker attach",
    "serve_replica_ready": "replica loop attach acknowledgement",
}

_EPS = 1e-4


def _jsonable(v: Any) -> Any:
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


class ProtocolTracer:
    """Append-only JSONL event recorder with a Lamport clock.

    One instance per process; every event costs one lock + one buffered
    line write, paid ONLY while installed (the rpc layer guards each hook
    behind ``if TRACE is not None``). The clock is process-global and
    merged from incoming frame clocks (``_lc``), so multi-process traces
    interleave causally; in the single-process test topology (GCS +
    daemons in-process, workers as subprocesses whose frames are clocked
    at the receiving daemon) the clock is a total order consistent with
    program order under the GCS/daemon locks.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._clock = 0
        self._pid = os.getpid()
        self._f = open(path, "a", encoding="utf-8")
        self.closed = False

    def _emit(self, rec: Dict[str, Any]) -> int:
        with self._lock:
            self._clock += 1
            rec["c"] = self._clock
            rec["pid"] = self._pid
            if not self.closed:
                self._f.write(json.dumps(rec, default=_jsonable) + "\n")
                self._f.flush()
            return self._clock

    # ------------------------------------------------------- rpc hooks

    def on_send(self, src: str, dst: str, method: Optional[str]) -> int:
        """Client-side frame send; the returned clock rides the frame as
        ``_lc`` so the receiving process can merge it."""
        return self._emit({"t": "send", "src": src, "dst": dst, "m": method})

    def on_recv(self, src: str, dst: str, method: Optional[str],
                remote_clock: Optional[int]) -> None:
        with self._lock:
            if remote_clock is not None and remote_clock > self._clock:
                self._clock = remote_clock
        self._emit({"t": "recv", "src": src, "dst": dst, "m": method})

    def on_push(self, src: str, dst: str, channel: Optional[str]) -> None:
        self._emit({"t": "push", "src": src, "dst": dst, "ch": channel})

    # ---------------------------------------------------- apply events

    def apply(self, kind: str, **fields: Any) -> int:
        """Application-level state mutation (GCS/daemon/client hooks).
        Returns the event's Lamport clock — shm channels stamp it into
        their header so the peer process can merge it (frames there never
        cross the RPC layer, where ``_lc`` would normally carry it)."""
        rec: Dict[str, Any] = {"t": "apply", "k": kind}
        rec.update(fields)
        return self._emit(rec)

    def merge_clock(self, remote_clock: Optional[int]) -> None:
        """Fold a peer clock received out-of-band (e.g. a channel header
        word) into this process's clock, preserving happens-before."""
        if not remote_clock:
            return
        with self._lock:
            if remote_clock > self._clock:
                self._clock = int(remote_clock)

    def close(self) -> None:
        with self._lock:
            self.closed = True
            try:
                self._f.close()
            except OSError:
                pass


# ------------------------------------------------------------ activation

# Flight recorder displaced by install() (ray_tpu.obs installs a bounded
# in-memory ring as the default rpc.TRACE): uninstall() puts it back so
# the always-on black box survives opt-in tracing sessions.
_displaced = None


def install(path: str) -> ProtocolTracer:
    """Make a fresh tracer writing to ``path`` the process-wide trace
    plane (``cluster/rpc.py`` hooks + every apply-event site)."""
    global _displaced
    from ray_tpu.cluster import rpc as _rpc

    tracer = ProtocolTracer(path)
    prev = _rpc.TRACE
    if prev is not None and getattr(prev, "is_flight_recorder", False):
        _displaced = prev
    _rpc.TRACE = tracer
    return tracer


def uninstall() -> None:
    global _displaced
    from ray_tpu.cluster import rpc as _rpc

    tracer = _rpc.TRACE
    if tracer is not None and getattr(tracer, "is_flight_recorder", False):
        return  # nothing opt-in is installed; keep the recorder running
    _rpc.TRACE, _displaced = _displaced, None
    if tracer is not None:
        tracer.close()


def active() -> Optional[ProtocolTracer]:
    from ray_tpu.cluster import rpc as _rpc

    return _rpc.TRACE


def install_from_env() -> Optional[ProtocolTracer]:
    path = os.environ.get(ENV_TRACE)
    if not path:
        return None
    return install(path)


# -------------------------------------------------------------- checking


@dataclasses.dataclass
class Violation:
    kind: str
    message: str
    clock: int

    def format(self) -> str:
        return f"[{self.kind}] c={self.clock}: {self.message}"


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trace, totally ordered by (clock, pid, file order).
    Tolerates a torn final line (a killed process mid-write)."""
    events: List[Tuple[int, int, int, Dict]] = []
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail from a killed writer
            events.append((int(ev.get("c", 0)), int(ev.get("pid", 0)), i, ev))
    events.sort(key=lambda t: (t[0], t[1], t[2]))
    return [ev for _c, _p, _i, ev in events]


class InvariantChecker:
    """Replays a trace's apply events against the protocol invariants."""

    def __init__(self):
        self.violations: List[Violation] = []
        # capacity model
        self.node_total: Dict[str, Dict[str, float]] = {}
        self.node_alive: Dict[str, bool] = {}
        # node -> {ledger_key: resources}; keys are task ids, actor-hold
        # ids, or ("pg", pg_id, bundle_index) tuples
        self.ledger: Dict[str, Dict[Any, Dict[str, float]]] = {}
        self.wiped: set = set()  # ledger keys erased by node death/reset
        # exactly-once: task -> node of the outstanding dispatch
        self.outstanding: Dict[str, str] = {}
        # straggler speculation: task -> {spec ledger key -> node} of
        # outstanding speculative copies. Every copy must end as the
        # winner (task_done from its node), a spec_cancel loser, a
        # spec_promote (new primary), or a node_dead wipe — anything
        # left at a strict_terminal check leaked a capacity hold
        self.spec_out: Dict[str, Dict[str, str]] = {}
        # PG 2PC daemon-side state per (node, pg, bundle)
        self.pg2pc: Dict[Tuple, str] = {}
        # actor ordering: (owner, actor, worker) -> last seq
        self.actor_seq: Dict[Tuple, int] = {}
        # borrows: outstanding (oid, worker) registrations
        self.borrows: set = set()
        # admission conservation: task -> net admit count (enter - exit);
        # a duplicate submission legally sits at 2 until intake dedupes
        self.admitted: Dict[str, int] = {}
        # object lifecycle: oid -> {"nodes": set, "freed": clock|None,
        #                           "put_after_free": bool}
        self.objects: Dict[str, Dict[str, Any]] = {}
        # compiled-DAG channels: key -> {"w": last written seq,
        # "r": last read seq, "reads_seen"/"writes_seen": bool}. The
        # cross-side checks (write overrun, read-before-write) arm only
        # once BOTH sides are witnessed on the edge — a topology where
        # only one end traces (e.g. the driver with worker subprocesses
        # lacking RAY_TPU_TRACE_FILE) must not self-flag; the same-side
        # seq-continuity checks always hold.
        self.channels: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------ helpers

    def _bad(self, kind: str, clock: int, msg: str) -> None:
        self.violations.append(Violation(kind, msg, clock))

    @staticmethod
    def _res(v: Any) -> Dict[str, float]:
        return {str(k): float(x) for k, x in (v or {}).items()}

    def _alloc(self, clock: int, node: str, key: Any,
               res: Dict[str, float]) -> None:
        led = self.ledger.setdefault(node, {})
        if key in led:
            self._bad("capacity", clock,
                      f"allocation key {key!r} on {node} allocated twice "
                      "without release")
            return
        led[key] = res
        self.wiped.discard(key)
        total = self.node_total.get(node)
        if total is None:
            return
        sums: Dict[str, float] = {}
        for r in led.values():
            for name, amt in r.items():
                sums[name] = sums.get(name, 0.0) + amt
        for name, amt in sums.items():
            if amt > total.get(name, 0.0) + _EPS:
                self._bad("capacity", clock,
                          f"node {node} oversubscribed on {name}: "
                          f"{amt:.3f} outstanding > {total.get(name, 0.0):.3f} "
                          f"total (alloc {key!r})")

    def _release(self, clock: int, key: Any, node: Optional[str]) -> None:
        # a LIVE ledger entry always wins over a stale wiped marker: an
        # actor-hold wiped by one node's death can be re-created on a new
        # node (restart) and must release normally there
        for n, led in self.ledger.items():
            if key in led:
                if node is not None and n != node:
                    self._bad("capacity", clock,
                              f"release of {key!r} on {node} but the "
                              f"allocation lives on {n}")
                del led[key]
                self.wiped.discard(key)
                return
        if key in self.wiped:
            self.wiped.discard(key)
            return  # released after its node died: the wipe already covered it
        self._bad("capacity", clock,
                  f"release of {key!r} with no outstanding allocation "
                  "(double-release or conservation drift)")

    def _wipe_node(self, node: str) -> None:
        for key in list(self.ledger.get(node, {})):
            self.wiped.add(key)
        self.ledger[node] = {}
        for task, n in list(self.outstanding.items()):
            if n == node:
                del self.outstanding[task]
        # speculative copies hosted on the dead node die with it (their
        # ledger entries were wiped above; no cancel/release follows)
        for task, m in list(self.spec_out.items()):
            for k in [k for k, n in m.items() if n == node]:
                del m[k]
            if not m:
                del self.spec_out[task]

    # -------------------------------------------------------------- apply

    def run(self, events: List[Dict[str, Any]],
            strict_terminal: bool = False) -> List[Violation]:
        for ev in events:
            if ev.get("t") != "apply":
                continue
            handler = getattr(self, "_on_" + ev.get("k", ""), None)
            if handler is not None:
                handler(ev)
        if strict_terminal:
            clock = events[-1].get("c", 0) if events else 0
            for oid_worker in sorted(self.borrows):
                self._bad("borrow", clock,
                          f"borrow {oid_worker!r} never released "
                          "(terminal count nonzero)")
            for task in sorted(self.admitted):
                self._bad("admission", clock,
                          f"task {task} admitted but never terminally "
                          "resolved (admission conservation: a silent "
                          "drop or a stranded queue entry)")
            for task in sorted(self.spec_out):
                self._bad("speculation", clock,
                          f"speculative copies of {task} never resolved "
                          "(no win, cancel, promote, or node wipe): "
                          f"{sorted(self.spec_out[task])}")
        return self.violations

    def _on_node(self, ev: Dict) -> None:
        node = ev["node"]
        if ev.get("revived") or node not in self.node_total:
            # fresh row or revival after death: availability reset, so the
            # ledger resets with it
            self._wipe_node(node)
            self.node_total[node] = self._res(ev.get("resources"))
        # live connection bounce (revived=False on a known node): the GCS
        # keeps the row as-is, so the ledger keeps its entries
        self.node_alive[node] = True

    def _on_node_dead(self, ev: Dict) -> None:
        node = ev["node"]
        self.node_alive[node] = False
        self._wipe_node(node)

    def _on_dispatch(self, ev: Dict) -> None:
        task, node = ev["task"], ev["node"]
        if ev.get("speculative"):
            # straggler speculation: a concurrent SECOND execution of the
            # same task is legal — under its OWN ledger key (task~sN), so
            # capacity conservation still pairs per execution — but only
            # while the primary dispatch is outstanding
            key = ev.get("key") or f"{task}~s?"
            if task not in self.outstanding:
                self._bad("speculation", ev["c"],
                          f"speculative copy {key!r} launched with no "
                          "outstanding primary dispatch")
            if not self.node_alive.get(node, False):
                self._bad("capacity", ev["c"],
                          f"speculative copy {key!r} dispatched to "
                          f"dead/unknown node {node}")
            self.spec_out.setdefault(task, {})[key] = node
            self._alloc(ev["c"], node, key, self._res(ev.get("res")))
            return
        if task in self.outstanding:
            self._bad("exactly-once", ev["c"],
                      f"task {task} dispatched to {node} while an earlier "
                      f"dispatch to {self.outstanding[task]} is still "
                      "outstanding")
        self.outstanding[task] = node
        if not self.node_alive.get(node, False):
            self._bad("capacity", ev["c"],
                      f"task {task} dispatched to dead/unknown node {node}")
        # PG-riding tasks debit their bundle, not the node: ledger entry is
        # empty but still keyed so the release pairs up
        self._alloc(ev["c"], node, task,
                    {} if ev.get("pg") else self._res(ev.get("res")))

    def _on_task_done(self, ev: Dict) -> None:
        task = ev["task"]
        if task not in self.outstanding:
            self._bad("exactly-once", ev["c"],
                      f"task_done for {task} applied with no outstanding "
                      "dispatch — a resend/duplicate escaped the dedupe")
            return
        del self.outstanding[task]
        # a speculative copy on the REPORTING node is the winner: its
        # ledger entry releases through the normal release event; every
        # other copy must follow with a spec_cancel (checked terminal)
        m = self.spec_out.get(task)
        if m:
            for k in [k for k, n in m.items() if n == ev.get("node")]:
                del m[k]
            if not m:
                del self.spec_out[task]

    def _on_spec_cancel(self, ev: Dict) -> None:
        """A losing execution of a speculated task was cancelled. The
        capacity release rides a paired ``release`` event under the same
        ledger key; here we retire the speculation bookkeeping —
        cancel-conservation: each copy cancels at most once."""
        task, key = ev["task"], ev.get("key")
        if key == task:
            return  # the PRIMARY lost to a copy: outstanding already
            # resolved by the winning task_done apply
        m = self.spec_out.get(task)
        if m is None or key not in m:
            self._bad("speculation", ev["c"],
                      f"spec_cancel for {key!r} with no outstanding "
                      "speculative copy (double-cancel or phantom)")
            return
        del m[key]
        if not m:
            del self.spec_out[task]

    def _on_spec_promote(self, ev: Dict) -> None:
        """The primary's node died with a speculative copy surviving: the
        copy becomes the primary (its ledger key carries over — the
        eventual release pairs against it)."""
        task, node, key = ev["task"], ev["node"], ev.get("key")
        m = self.spec_out.get(task)
        if m is None or key not in m:
            self._bad("speculation", ev["c"],
                      f"spec_promote of {key!r} which is not an "
                      "outstanding speculative copy")
        else:
            del m[key]
            if not m:
                del self.spec_out[task]
        if task in self.outstanding:
            self._bad("speculation", ev["c"],
                      f"spec_promote of {task} while a primary dispatch "
                      "is still outstanding (promotion without a wipe)")
        self.outstanding[task] = node

    def _on_node_quarantine(self, ev: Dict) -> None:
        pass  # informational; capacity semantics ride release events

    def _on_task_done_dup(self, ev: Dict) -> None:
        pass  # informational: a dedup that worked

    def _on_retag(self, ev: Dict) -> None:
        old, new = ev["old"], ev["new"]
        for led in self.ledger.values():
            if old in led:
                led[new] = led.pop(old)
                # the hold key may carry a stale wiped marker from a
                # PREVIOUS incarnation's node death (actor restarts reuse
                # actor-hold-<id>); the fresh entry supersedes it
                self.wiped.discard(new)
                return
        if old in self.wiped:
            self.wiped.discard(old)
            self.wiped.add(new)

    def _on_release(self, ev: Dict) -> None:
        self._release(ev["c"], ev["key"], ev.get("node"))

    def _on_pg_stage(self, ev: Dict) -> None:
        pg = ev["pg"]
        for led in self.ledger.values():
            for key in led:
                if isinstance(key, (tuple, list)) and len(key) == 3 \
                        and key[0] == "pg" and key[1] == pg:
                    self._bad("pg-2pc", ev["c"],
                              f"pg {pg} staged while bundle allocation "
                              f"{key!r} is still outstanding")
        for i, (node, bundle) in enumerate(
            zip(ev.get("nodes") or (), ev.get("bundles") or ())
        ):
            self._alloc(ev["c"], node, ("pg", pg, i), self._res(bundle))

    def _on_pg_reapply(self, ev: Dict) -> None:
        # snapshot-restored bundle re-applied as its node re-registered;
        # ordinal-keyed (bundle indices are not in the snapshot tuple)
        node, pg = ev["node"], ev["pg"]
        n = sum(
            1 for led in self.ledger.values() for key in led
            if isinstance(key, (tuple, list)) and key[0] == "pg"
            and key[1] == pg
        )
        self._alloc(ev["c"], node, ("pg", pg, f"reapply-{n}"),
                    self._res(ev.get("res")))

    def _on_pg_release(self, ev: Dict) -> None:
        pg = ev["pg"]
        for led in self.ledger.values():
            for key in list(led):
                if isinstance(key, (tuple, list)) and len(key) == 3 \
                        and key[0] == "pg" and key[1] == pg:
                    del led[key]
        for key in list(self.wiped):
            if isinstance(key, (tuple, list)) and key and key[0] == "pg" \
                    and key[1] == pg:
                self.wiped.discard(key)

    def _on_pg_created(self, ev: Dict) -> None:
        pass  # allocations persist for the PG's lifetime — nothing to move

    def _on_pg_prepare(self, ev: Dict) -> None:
        if ev.get("ok"):
            self.pg2pc[(ev["node"], ev["pg"], ev["bundle"])] = "PREPARED"

    def _on_pg_commit(self, ev: Dict) -> None:
        key = (ev["node"], ev["pg"], ev["bundle"])
        if not ev.get("ok"):
            return  # refused commit (no surviving prepare): legal outcome
        if not ev.get("transition", True):
            return  # idempotent re-commit of an already-committed bundle
        if self.pg2pc.get(key) != "PREPARED":
            self._bad("pg-2pc", ev["c"],
                      f"bundle {key!r} committed from state "
                      f"{self.pg2pc.get(key, 'IDLE')!r} (commit without "
                      "prepare / commit after abort)")
        self.pg2pc[key] = "COMMITTED"

    def _on_pg_return(self, ev: Dict) -> None:
        self.pg2pc.pop((ev["node"], ev["pg"], ev["bundle"]), None)

    def _on_actor_exec(self, ev: Dict) -> None:
        seq = ev.get("seq")
        if seq is None:
            return
        key = (ev.get("owner"), ev["actor"], ev.get("worker"))
        last = self.actor_seq.get(key)
        if last is not None and int(seq) <= last:
            self._bad("actor-seq", ev["c"],
                      f"actor {ev['actor']} executed seq {seq} after seq "
                      f"{last} for the same caller on the same worker "
                      "(submission-order execution broken)")
        else:
            self.actor_seq[key] = int(seq)

    # --- admission conservation (overload control plane) ---

    def _on_admit(self, ev: Dict) -> None:
        t = ev["task"]
        self.admitted[t] = self.admitted.get(t, 0) + 1

    def _on_admit_exit(self, ev: Dict) -> None:
        t = ev["task"]
        n = self.admitted.get(t, 0) - 1
        if n < 0:
            self._bad("admission", ev["c"],
                      f"task {t} exited the admission ledger without a "
                      "matching admit (exit-without-admit)")
            self.admitted.pop(t, None)
        elif n == 0:
            self.admitted.pop(t, None)
        else:
            self.admitted[t] = n

    def _on_admit_reject(self, ev: Dict) -> None:
        pass  # typed rejection: terminal by construction, never admitted

    def _on_node_drain(self, ev: Dict) -> None:
        pass  # informational; capacity semantics ride release events

    def _on_borrow_reg(self, ev: Dict) -> None:
        self.borrows.add((ev["oid"], ev.get("worker")))

    def _on_borrow_rel(self, ev: Dict) -> None:
        key = (ev["oid"], ev.get("worker"))
        if key not in self.borrows:
            self._bad("borrow", ev["c"],
                      f"borrow release for {key!r} without a registration "
                      "(releases exceed registers)")
            return
        self.borrows.discard(key)

    def _on_obj_put(self, ev: Dict) -> None:
        o = self.objects.setdefault(
            ev["oid"], {"nodes": set(), "freed": None}
        )
        o["nodes"].add(ev.get("node"))
        if o["freed"] is not None:
            o["freed"] = None  # legal re-creation (retry / reconstruction)

    def _on_obj_loc(self, ev: Dict) -> None:
        oid, node = ev["oid"], ev.get("node")
        o = self.objects.get(oid)
        if o is None or node not in o["nodes"]:
            self._bad("object-lifecycle", ev["c"],
                      f"location of {oid[:12]} on {node} recorded without "
                      "a store put on that node")
            return
        if o["freed"] is not None:
            self._bad("object-lifecycle", ev["c"],
                      f"location of {oid[:12]} on {node} re-surfaced after "
                      "free with no re-creation (ghost directory entry)")

    def _on_obj_free(self, ev: Dict) -> None:
        o = self.objects.get(ev["oid"])
        if o is not None:
            o["freed"] = ev["c"]

    # --- compiled-DAG channel alternation (ray_tpu/dag/channel.py) ---

    def _chan(self, key: str) -> Dict[str, Any]:
        return self.channels.setdefault(
            key, {"w": 0, "r": 0, "reads_seen": False, "writes_seen": False}
        )

    def _on_chan_write(self, ev: Dict) -> None:
        st = self._chan(ev["chan"])
        seq = int(ev["seq"])
        st["writes_seen"] = True
        if seq != st["w"] + 1:
            self._bad("channel", ev["c"],
                      f"channel {ev['chan']}: write seq {seq} after seq "
                      f"{st['w']} (gap or duplicate — single-writer seq "
                      "must advance by exactly 1)")
        elif st["reads_seen"] and st["r"] != st["w"]:
            self._bad("channel", ev["c"],
                      f"channel {ev['chan']}: write seq {seq} before frame "
                      f"{st['r'] + 1} was consumed (writer overran the "
                      "reader ack — backpressure broken)")
        st["w"] = max(st["w"], seq)

    def _on_chan_read(self, ev: Dict) -> None:
        st = self._chan(ev["chan"])
        seq = int(ev["seq"])
        st["reads_seen"] = True
        if st["writes_seen"] and seq > st["w"]:
            self._bad("channel", ev["c"],
                      f"channel {ev['chan']}: read seq {seq} before it was "
                      f"written (last write {st['w']}) — read-before-write")
        elif seq != st["r"] + 1:
            self._bad("channel", ev["c"],
                      f"channel {ev['chan']}: read seq {seq} after seq "
                      f"{st['r']} (skipped or re-read a frame)")
        st["r"] = max(st["r"], seq)


def check_trace(path: str, strict_terminal: bool = False) -> List[Violation]:
    """Replay the JSONL trace at ``path`` and return every invariant
    violation (empty list = the run was protocol-clean)."""
    return InvariantChecker().run(read_trace(path), strict_terminal)
