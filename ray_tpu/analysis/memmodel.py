"""Exhaustive word-level model checker for the compiled-DAG seqlock channel.

PR 6's explorer machine-checks the control plane; this module gives the
*data plane* — the single-writer/single-reader seqlock shm channel in
``ray_tpu/dag/channel.py`` that every compiled-graph iteration rides —
the same treatment, one abstraction level down: individual header-word
loads/stores and payload copies are the scheduling alphabet, not RPC
deliveries.

Dynamic half. The channel protocol runs as *actor op generators*
(writer, reader, the MultiOutput dual-channel writer with a second
reader, the daemon death-sweep poker, a graceful closer) over a
:class:`VirtualMem` — a virtual channel memory whose every word op is a
step on a controlled schedule. The payload memcpy is deliberately
non-atomic (two chunk micro-ops), so torn frames are representable; each
end tracks its own mapped size, so grow-in-place ``ftruncate``+remap
races are representable; a *kill* step can preempt the writer at any op
(crash consistency: the reader must then see the old intact frame or
``CLOSED|ERROR`` — never a torn or stale-seq frame), after which the
poker models the daemon's death sweep. Schedules are enumerated by the
exact engine ``explore.py`` uses — bounded-depth DFS with
persistent-set conflict pruning (read/write-aware here: two loads of the
same word commute), seeded-random sampling beyond the bound, and
delta-debug shrinking of any violation to a minimal replay file that
``python -m ray_tpu.analysis --replay`` re-executes deterministically.

Static half (what keeps the model honest). The checked model is only as
good as its correspondence to the real code, so
:func:`verify_op_sequences` AST-extracts the op sequences of
``Channel.write`` / ``Channel.read`` / ``Channel.close`` /
``poke_error`` from ``dag/channel.py`` — every ``self._get/_put`` /
``mem.load/store`` / payload / grow / remap call, in source order, with
loop/optional structure — and matches them against
:data:`DECLARED_SEQUENCES`, the same table the actor generators
implement. The companion lint checkers (``chan-raw-header-access``,
``chan-publication-order`` in ``analysis/checkers.py``) enforce that no
code outside the :class:`~ray_tpu.dag.channel.ChannelMem` ops layer
touches header words at all, and that payload stores precede the
``version``/``ack`` publication. Same load-bearing pattern as the
invariant checker's METHOD_TABLE round-trip against ``--dump-protocol``.

``ray_tpu.dag.channel.SEEDED_BUGS`` re-introduces known protocol bugs
(``version-before-payload``, ``skip-remap-reread``) so the harness can
prove it still finds and shrinks them — the regression teeth.

Honesty notes: the model abstracts payload bytes to two seq-stamped
chunks (enough to represent torn/stale reads, not byte contents) and
lengths to small "units"; the adaptive spin/sleep wait collapses to a
single *park* step woken by stores to the watched words (timeouts are
explicit one-shot steps), so real-time behavior — how long a stall
lasts — is out of scope; only event orderings are checked.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import textwrap
import time as _time
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from ray_tpu.analysis.core import chan_word_of
from ray_tpu.analysis.explore import (
    GLOBAL_KEY,
    Chooser,
    ScheduleDiverged,
    dfs_explore,
    shrink_generic,
)
from ray_tpu.analysis.invariants import Violation

#: (channel.SEEDED_BUGS name, scenario that exhibits it) — the ONE table
#: the CI teeth (lint_gate --memmodel), bench.py's detection-cost trail,
#: and the regression tests all iterate; a bug added to
#: channel.SEEDED_BUGS without a row here is invisible to all three
#: (explore_channel accepts unknown names without error).
SEEDED_BUG_SCENARIOS: Tuple[Tuple[str, str], ...] = (
    ("version-before-payload", "spsc-alternation"),
    ("skip-remap-reread", "late-attach-grow"),
)

KNOWN_SEEDED_BUGS = tuple(b for b, _ in SEEDED_BUG_SCENARIOS)

#: header words the model schedules over, in channel.HEADER_LAYOUT
#: order (verify_op_sequences() cross-checks; duplicated here so the
#: model is readable without the runtime tree in scope). ``closed`` and
#: ``error`` are write-once blind-store words.
WORD_NAMES = (
    "magic", "closed", "error", "version", "ack", "len", "wclock",
    "rclock", "capacity", "cpid", "apid",
)


# ------------------------------------------------------ declared model
#
# One entry per op: (kind, target, flags) where kind ∈ {load, store,
# grow, remap}, target is a header word name / "payload" / "", and flags
# is "" (unconditional), "loop" (inside the spin-wait loop — runs ≥ once
# per wakeup), or "opt" (branch-dependent: grow path, tracer installed).
# These tables are BOTH what the actor generators below implement AND
# what verify_op_sequences() matches against the AST of the real
# dag/channel.py — edit one side and the round-trip gate fails.

WRITE_SEQ: Tuple[Tuple[str, str, str], ...] = (
    ("load", "error", "loop"),
    ("load", "closed", "loop"),
    ("load", "version", "loop"),
    ("load", "ack", "loop"),
    ("load", "capacity", ""),
    ("grow", "", "opt"),
    ("store", "capacity", "opt"),
    ("store", "payload", ""),
    ("store", "len", ""),
    ("load", "rclock", "opt"),
    ("store", "wclock", "opt"),
    ("store", "version", ""),
)

# NOTE the load order in the wait loop: ``closed`` strictly before
# ``version``/``ack``. The writer publishes its last commit before
# closing, so closed==1 here implies the version load already sees every
# committed frame; the reversed order (the original code) let a racing
# graceful close drop a committed final frame — found by this checker.
READ_SEQ: Tuple[Tuple[str, str, str], ...] = (
    ("load", "error", "loop"),
    ("load", "closed", "loop"),
    ("load", "ack", "loop"),
    ("load", "version", "loop"),
    ("load", "len", ""),
    ("remap", "", "opt"),
    ("load", "payload", ""),
    ("load", "wclock", "opt"),
    ("store", "rclock", "opt"),
    ("store", "ack", ""),
)

# Blind one-shot stores, NO load: a load-OR-store close() racing
# poke_error() loses whichever bit the slower store did not carry —
# found by this checker (close-vs-poke scenario), fixed by splitting
# the flag word and forbidding the read-modify-write. ``error`` lands
# BEFORE ``closed``: a peer waking between the stores must already see
# the fatal bit rather than drain a death-close like a graceful one.
CLOSE_SEQ: Tuple[Tuple[str, str, str], ...] = (
    ("store", "error", "opt"),
    ("store", "closed", ""),
)

POKE_SEQ: Tuple[Tuple[str, str, str], ...] = (
    ("store", "error", ""),
    ("store", "closed", ""),
)

DECLARED_SEQUENCES: Dict[str, Tuple[Tuple[str, str, str], ...]] = {
    "write": WRITE_SEQ,
    "read": READ_SEQ,
    "close": CLOSE_SEQ,
    "poke_error": POKE_SEQ,
}


# ------------------------------------------------------- virtual memory


class VirtualMem:
    """One channel's virtual shared memory: the header words, the
    payload as two seq-stamped chunks (a non-atomic memcpy), the backing
    file size, and each attached end's mapped size. Lengths are abstract
    *units* (capacity 2 = a frame of len ≤ 2 fits without growing)."""

    def __init__(self, name: str, capacity: int):
        from ray_tpu.dag.channel import MAGIC

        self.name = name
        self.words: Dict[str, int] = {w: 0 for w in WORD_NAMES}
        self.words["magic"] = MAGIC
        self.words["capacity"] = capacity
        self.chunks: List[int] = [0, 0]  # seq that last wrote each half
        self.file_units = capacity
        self.mapped: Dict[str, int] = {}  # actor -> units mapped
        self.epoch: Dict[str, int] = {w: 0 for w in WORD_NAMES}

    def attach(self, actor: str) -> None:
        self.mapped.setdefault(actor, self.words["capacity"])


# ------------------------------------------------------------- actors
#
# Generators yield op tuples (kind, chan, a, b) and receive the op's
# result (loads: the value; park: "woken"/"timeout"). They implement
# DECLARED_SEQUENCES — the payload entry expands to the two chunk
# micro-ops, the spin-wait loop to a park step — and gate on the same
# SEEDED_BUGS names as the real channel code.


def _write_one(world: "ChannelWorld", chan: str, name: str, need: int,
               bugs: FrozenSet[str]):
    """One Channel.write() on ``chan``; returns "ok" / "closed" /
    "timeout" (timeout = zero-commit: nothing of this frame hit shared
    memory, the CompiledDAG.execute rewind precondition)."""
    while True:
        err = yield ("load", chan, "error", None)
        closed = yield ("load", chan, "closed", None)
        if err or closed:
            return "closed"
        version = yield ("load", chan, "version", None)
        ack = yield ("load", chan, "ack", None)
        if ack == version:
            break
        r = yield ("park", chan, ("error", "closed", "ack"), None)
        if r == "timeout":
            return "timeout"
    seq = version + 1
    cap = yield ("load", chan, "capacity", None)
    if need > cap:
        new_cap = max(need, 2 * cap)
        yield ("grow", chan, new_cap, None)
        yield ("store", chan, "capacity", new_cap)
    world.declare_frame(chan, seq, need)
    if "version-before-payload" in bugs:
        # SEEDED BUG mirror of channel.write's gated early publication
        yield ("store", chan, "version", seq)
    yield ("store_chunk", chan, 0, (seq, need))
    yield ("store_chunk", chan, 1, (seq, need))
    yield ("store", chan, "len", need)
    yield ("store", chan, "version", seq)
    return "ok"


def _close_one(chan: str, error: bool = False):
    if error:
        yield ("store", chan, "error", 1)
    yield ("store", chan, "closed", 1)


def _writer(world: "ChannelWorld", name: str, chans: Sequence[str],
            frames: Sequence[int], bugs: FrozenSet[str],
            close_after: bool = True, rewind_on_timeout: bool = False):
    """Stage writer: commits each frame to every channel in ``chans`` in
    order (one channel = plain SPSC; two = the MultiOutput dual-channel
    / partial-input-commit shape), then closes gracefully."""
    fi = 0
    while fi < len(frames):
        for chan in chans:
            r = yield from _write_one(world, chan, name, frames[fi], bugs)
            if r == "closed":
                world.outcome(name, ("closed", fi))
                return
            if r == "timeout":
                world.outcome(name, ("timeout", fi))
                if not rewind_on_timeout:
                    return
                # zero-commit rewind: retry the SAME frame/seq later
                break
        else:
            world.outcome(name, ("committed", fi + 1))
            fi += 1
    if close_after:
        for chan in chans:
            yield from _close_one(chan)
    world.outcome(name, ("done", fi))


def _reader(world: "ChannelWorld", name: str, chan: str,
            bugs: FrozenSet[str]):
    """Driver/stage reader: consumes frames until the channel reports
    CLOSED (drained) or ERROR, recording everything it observed."""
    got: List[int] = []
    while True:
        while True:
            err = yield ("load", chan, "error", None)
            if err:
                world.outcome(name, ("error-closed", tuple(got)))
                return
            closed = yield ("load", chan, "closed", None)
            ack = yield ("load", chan, "ack", None)
            version = yield ("load", chan, "version", None)
            if version > ack:
                break
            if closed:
                world.outcome(name, ("closed-drained", tuple(got)))
                return
            r = yield ("park", chan, ("error", "closed", "version"), None)
            if r == "timeout":
                world.outcome(name, ("timeout", tuple(got)))
                return
        seq = version
        need = yield ("load", chan, "len", None)
        world.check_len(chan, name, seq, need)
        if "skip-remap-reread" not in bugs:
            if need > world.mem(chan).mapped[name]:
                yield ("remap", chan, None, None)
        yield ("load_chunk", chan, 0, (seq, need))
        yield ("load_chunk", chan, 1, (seq, need))
        world.check_seq(chan, name, seq, got)
        yield ("store", chan, "ack", seq)
        got.append(seq)


def _poker(chans: Sequence[str]):
    """The daemon's death sweep: flag every channel of the dead worker's
    DAG CLOSED|ERROR (channel.poke_error per channel)."""
    for chan in chans:
        yield from _close_one(chan, error=True)


def _closer(chans: Sequence[str]):
    """Graceful driver teardown: CLOSED without ERROR (stages drain)."""
    for chan in chans:
        yield from _close_one(chan)


# -------------------------------------------------------------- world


@dataclasses.dataclass
class _Actor:
    name: str
    gen: Any
    pending: Optional[tuple] = None
    label: str = ""
    ops: int = 0
    parked: Optional[Tuple[str, Tuple[str, ...]]] = None  # (chan, words)
    done: bool = False
    killed: bool = False
    #: (chan, word) -> store epoch at this actor's last load of it; a
    #: park is a no-op (stays runnable) when a watched word moved since
    #: the actor's last look — otherwise a store landing between the
    #: spin-loop's reads and the park step would be missed and the actor
    #: would sleep forever on a condition that already holds
    seen_epochs: Dict[Tuple[str, str], int] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class _ExtraStep:
    label: str
    fire: Callable[[], None]
    enabled: Callable[[], bool]
    keys: FrozenSet


class ChannelWorld:
    """One execution of a channel scenario under a controlled schedule:
    the actors' pending ops (plus kill/timeout steps) are the step set;
    every store enforces the word-level invariants; every completed read
    is checked for torn/stale frames."""

    def __init__(self, chooser: Chooser, bugs: Sequence[str] = (),
                 step_limit: int = 300):
        self.chooser = chooser
        self.bugs = frozenset(bugs)
        self.step_limit = step_limit
        self.mems: Dict[str, VirtualMem] = {}
        self.actors: Dict[str, _Actor] = {}
        self.extra: List[_ExtraStep] = []
        self.schedule: List[str] = []
        self.options_at: List[Tuple[str, ...]] = []
        self.keys_of: Dict[str, FrozenSet] = {}
        self.violations: List[Violation] = []
        self.outcomes: Dict[str, List[tuple]] = {}
        self.frame_lens: Dict[Tuple[str, int], int] = {}
        #: version-ordering pairs: (behind, ahead) — chan `behind` may
        #: never commit past chan `ahead` (MultiOutput branch order)
        self.order_pairs: List[Tuple[str, str]] = []
        self.crash_point: Optional[str] = None
        self.stopped_early = False

    # ------------------------------------------------------------ build

    def add_channel(self, name: str, capacity: int) -> None:
        self.mems[name] = VirtualMem(name, capacity)

    def mem(self, chan: str) -> VirtualMem:
        return self.mems[chan]

    def add_actor(self, name: str, gen) -> None:
        a = _Actor(name, gen)
        self.actors[name] = a
        for m in self.mems.values():
            m.attach(name)
        self._advance(a, first=True)

    def add_kill(self, victim: str, spawn_poker_on: Sequence[str]) -> None:
        """A schedulable kill of ``victim`` at ANY of its op positions
        (keys=GLOBAL so the DFS branches it everywhere), followed by the
        daemon death-sweep poker over ``spawn_poker_on``."""

        def fire():
            a = self.actors[victim]
            a.gen.close()
            a.done = True
            a.killed = True
            a.parked = None
            self.crash_point = a.label or "start"
            self.outcome(victim, ("killed-at", a.label))
            self.add_actor("poker", _poker(tuple(spawn_poker_on)))

        self.extra.append(_ExtraStep(
            label=f"kill:{victim}", fire=fire,
            enabled=lambda: not self.actors[victim].done,
            keys=frozenset({GLOBAL_KEY}),
        ))

    def add_timeout(self, target: str) -> None:
        """One-shot deadline expiry for ``target``: wakes its park with
        "timeout" (the ChannelTimeoutError path)."""
        step = _ExtraStep(
            label=f"timeout:{target}", fire=lambda: None,
            enabled=lambda: self.actors[target].parked is not None,
            keys=frozenset({GLOBAL_KEY}),
        )

        def fire(step=step):
            self.extra.remove(step)
            self._wake(self.actors[target], "timeout")

        step.fire = fire
        self.extra.append(step)

    # ------------------------------------------------------- bookkeeping

    def outcome(self, name: str, what: tuple) -> None:
        self.outcomes.setdefault(name, []).append(what)

    def declare_frame(self, chan: str, seq: int, need: int) -> None:
        self.frame_lens[(chan, seq)] = need

    def violate(self, kind: str, msg: str) -> None:
        self.violations.append(Violation(kind, msg, len(self.schedule)))

    def check_len(self, chan: str, reader: str, seq: int,
                  need: int) -> None:
        # checked at the len LOAD (the earliest observable point of a
        # header tear) so violating replays shrink to the minimum prefix
        declared = self.frame_lens.get((chan, seq))
        if declared is not None and declared != need:
            self.violate(
                "torn-frame",
                f"{reader} read seq {seq} on {chan} with len {need}, "
                f"writer declared {declared} (header tear)",
            )

    def check_seq(self, chan: str, reader: str, seq: int,
                  got: List[int]) -> None:
        last = got[-1] if got else 0
        if seq != last + 1:
            self.violate(
                "stale-seq",
                f"{reader} consumed seq {seq} on {chan} after {last} "
                "(dup/skipped frame)",
            )

    # ---------------------------------------------------------- op exec

    def _op_label(self, op: tuple) -> str:
        kind, chan, a, _b = op
        if kind in ("load", "store"):
            return f"{kind}:{chan}.{a}"
        if kind in ("load_chunk", "store_chunk"):
            return f"{kind}:{chan}.{a}"
        if kind == "park":
            return f"park:{chan}." + "+".join(a)
        return f"{kind}:{chan}"

    def _op_keys(self, op: tuple) -> FrozenSet:
        kind, chan, a, _b = op
        if kind == "load":
            return frozenset({("r", chan, a)})
        if kind == "store":
            return frozenset({("w", chan, a)})
        if kind == "load_chunk":
            return frozenset({("r", chan, "payload")})
        if kind == "store_chunk":
            return frozenset({("w", chan, "payload")})
        if kind == "grow":
            return frozenset({("w", chan, "file")})
        if kind == "remap":
            return frozenset({("r", chan, "file")})
        if kind == "park":
            return frozenset(("r", chan, w) for w in a)
        return frozenset({GLOBAL_KEY})

    def _store_invariants(self, mem: VirtualMem, word: str, value: int,
                          actor: str) -> None:
        cur = mem.words[word]
        if word == "magic" and value != cur:
            self.violate("magic-clobber",
                         f"{actor} rewrote magic on {mem.name}")
        elif word in ("closed", "error"):
            if value != 1:
                self.violate(
                    "flag-clear",
                    f"{actor} stored {value} to {word} on {mem.name}; "
                    "closed/error are write-once blind stores of 1 "
                    "(anything else can lose a racing close/poke)",
                )
        elif word == "version":
            if value not in (cur, cur + 1):
                self.violate(
                    "seq-skip",
                    f"{actor} moved version {cur} -> {value} on "
                    f"{mem.name} (must advance by exactly 1)",
                )
            if value > mem.words["ack"] + 1:
                self.violate(
                    "overrun",
                    f"{actor} committed seq {value} on {mem.name} with "
                    f"ack at {mem.words['ack']} (previous frame "
                    "unconsumed — SPSC alternation broken)",
                )
            for behind, ahead in self.order_pairs:
                if mem.name == behind and \
                        value > self.mems[ahead].words["version"]:
                    self.violate(
                        "cross-channel-order",
                        f"{actor} committed seq {value} on {behind} "
                        f"ahead of {ahead} (MultiOutput branch order)",
                    )
        elif word == "ack":
            if value != cur + 1:
                self.violate(
                    "ack-skip",
                    f"{actor} moved ack {cur} -> {value} on {mem.name}",
                )
            if value > mem.words["version"]:
                self.violate(
                    "ack-overrun",
                    f"{actor} acked seq {value} on {mem.name} beyond "
                    f"version {mem.words['version']}",
                )

    def _exec(self, actor: _Actor, op: tuple):
        kind, chan, a, b = op
        mem = self.mems[chan]
        if kind == "load":
            actor.seen_epochs[(chan, a)] = mem.epoch[a]
            return mem.words[a]
        if kind == "store":
            self._store_invariants(mem, a, b, actor.name)
            mem.words[a] = b
            mem.epoch[a] += 1
            for other in self.actors.values():
                if other.parked and other.parked[0] == chan and \
                        a in other.parked[1]:
                    self._wake(other, "woken")
            return None
        if kind == "store_chunk":
            seq, need = b
            if need > mem.mapped[actor.name]:
                self.violate(
                    "stale-mapping",
                    f"{actor.name} wrote payload of len {need} on "
                    f"{chan} with only {mem.mapped[actor.name]} mapped",
                )
            mem.chunks[a] = seq
            return None
        if kind == "load_chunk":
            seq, need = b
            if need > mem.mapped[actor.name]:
                self.violate(
                    "stale-mapping",
                    f"{actor.name} read payload of len {need} on {chan} "
                    f"with only {mem.mapped[actor.name]} mapped (missed "
                    "the grow-in-place remap)",
                )
            stamp = mem.chunks[a]
            # checked per chunk LOAD (earliest observable tear) — see
            # check_len
            if stamp != seq:
                self.violate(
                    "torn-frame",
                    f"{actor.name} read payload chunk {a} of seq {seq} "
                    f"on {chan} stamped {stamp} "
                    + ("(stale payload under a new seq)"
                       if a == 0 or stamp == mem.chunks[0]
                       else "(mid-copy tear)"),
                )
            return stamp
        if kind == "grow":
            mem.file_units = max(mem.file_units, a)
            mem.mapped[actor.name] = mem.file_units
            return None
        if kind == "remap":
            mem.mapped[actor.name] = mem.file_units
            return None
        if kind == "park":
            moved = any(
                mem.epoch[w] > actor.seen_epochs.get((chan, w), 0)
                for w in a
            )
            if moved:
                # a store to a watched word landed between this actor's
                # last look and the park: no-op, stay runnable
                return "woken"
            actor.parked = (chan, tuple(a))
            return None  # result delivered by _wake
        raise AssertionError(f"unknown op {op!r}")

    # ------------------------------------------------------- scheduling

    def _advance(self, actor: _Actor, first: bool = False,
                 send: Any = None) -> None:
        try:
            op = next(actor.gen) if first else actor.gen.send(send)
        except StopIteration:
            actor.done = True
            actor.pending = None
            return
        actor.pending = op
        actor.label = f"{actor.name}.{actor.ops}:{self._op_label(op)}"
        self.keys_of[actor.label] = self._op_keys(op)

    def _wake(self, actor: _Actor, result: str) -> None:
        actor.parked = None
        actor.ops += 1
        self._advance(actor, send=result)

    def _options(self) -> List[Tuple[str, Callable[[], None]]]:
        out: List[Tuple[str, Callable[[], None]]] = []
        for actor in self.actors.values():
            if actor.done or actor.parked is not None or \
                    actor.pending is None:
                continue
            out.append((actor.label, actor))
        for step in self.extra:
            if step.enabled():
                self.keys_of[step.label] = step.keys
                out.append((step.label, step))
        return out

    def _fire(self, chosen: str,
              options: List[Tuple[str, Any]]) -> None:
        target = dict(options)[chosen]
        if isinstance(target, _ExtraStep):
            target.fire()
            return
        actor = target
        op = actor.pending
        result = self._exec(actor, op)
        if op[0] == "park" and actor.parked is not None:
            return  # parked: resume comes through _wake
        actor.ops += 1
        self._advance(actor, send=result)

    def run(self) -> None:
        while True:
            options = self._options()
            if not options:
                parked = [a.name for a in self.actors.values()
                          if a.parked is not None]
                if parked:
                    self.violate(
                        "deadlock",
                        f"actors {parked} parked forever (no step can "
                        "wake them — a CLOSED/ERROR poke was lost?)",
                    )
                return
            if len(self.schedule) >= self.step_limit:
                self.violate(
                    "step-budget",
                    f"step budget exceeded ({self.step_limit}): the "
                    "scenario does not quiesce",
                )
                return
            labels = tuple(label for label, _ in options)
            chosen = self.chooser.choose(labels, at_interleave=False)
            if chosen is None:
                self.stopped_early = True
                return
            self.schedule.append(chosen)
            self.options_at.append(labels)
            self._fire(chosen, options)


# ----------------------------------------------------------- scenarios


@dataclasses.dataclass
class ChannelScenario:
    name: str
    description: str
    build: Callable[[ChannelWorld], None]
    postcheck: Optional[Callable[[ChannelWorld], List[str]]] = None


def _got(world: ChannelWorld, reader: str) -> Optional[Tuple[int, ...]]:
    for what in world.outcomes.get(reader, ()):
        if what[0] in ("closed-drained", "error-closed", "timeout"):
            return tuple(what[1])
    return None


def _check_reader(world: ChannelWorld, reader: str,
                  frames: Tuple[int, ...],
                  require_all: bool) -> List[str]:
    got = _got(world, reader)
    if got is None:
        return [f"{reader} never terminated (no closed/error outcome)"]
    want = tuple(range(1, len(frames) + 1))
    if require_all and got != want:
        return [f"{reader} consumed {got}, expected exactly {want}"]
    if got != want[:len(got)]:
        return [f"{reader} consumed {got}, not a prefix of {want}"]
    return []


def _build_spsc(world: ChannelWorld) -> None:
    world.add_channel("a", capacity=2)
    world.add_actor("writer", _writer(world, "writer", ("a",), (1, 2),
                                      world.bugs))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))


def _post_spsc(world: ChannelWorld) -> List[str]:
    return _check_reader(world, "reader", (1, 2), require_all=True)


def _build_kill(world: ChannelWorld) -> None:
    world.add_channel("a", capacity=2)
    world.add_actor("writer", _writer(world, "writer", ("a",), (1, 1),
                                      world.bugs))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))
    world.add_kill("writer", spawn_poker_on=("a",))


def _post_kill(world: ChannelWorld) -> List[str]:
    return _check_reader(world, "reader", (1, 1), require_all=False)


def _build_grow(world: ChannelWorld) -> None:
    world.add_channel("a", capacity=2)
    world.add_actor("writer", _writer(world, "writer", ("a",), (2, 4),
                                      world.bugs))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))


def _post_grow(world: ChannelWorld) -> List[str]:
    return _check_reader(world, "reader", (2, 4), require_all=True)


def _build_late_attach_grow(world: ChannelWorld) -> None:
    # pre-history: the writer grew the file 2 -> 4 units and committed a
    # len-4 frame BEFORE this world starts, but the reader's mapping
    # predates the grow (open_wait maps the file size at attach time) —
    # its very first read must take the remap path
    world.add_channel("a", capacity=4)
    mem = world.mem("a")
    mem.words["version"] = 1
    mem.words["len"] = 4
    mem.chunks = [1, 1]
    world.declare_frame("a", 1, 4)
    world.add_actor("writer", _writer(world, "writer", ("a",), (1,),
                                      world.bugs))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))
    mem.mapped["reader"] = 2  # attached before the grow


def _post_late_attach_grow(world: ChannelWorld) -> List[str]:
    # frame 1 is the pre-committed big frame, frame 2 the writer's
    return _check_reader(world, "reader", (4, 1), require_all=True)


def _build_close_vs_poke(world: ChannelWorld) -> None:
    world.add_channel("a", capacity=2)
    world.add_actor("writer", _writer(world, "writer", ("a",), (1, 1),
                                      world.bugs, close_after=False))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))
    world.add_actor("closer", _closer(("a",)))
    world.add_actor("poker", _poker(("a",)))


def _post_close_vs_poke(world: ChannelWorld) -> List[str]:
    return _check_reader(world, "reader", (1, 1), require_all=False)


def _build_timeout(world: ChannelWorld) -> None:
    world.add_channel("a", capacity=2)
    world.add_actor("writer", _writer(world, "writer", ("a",), (1, 1),
                                      world.bugs,
                                      rewind_on_timeout=True))
    world.add_actor("reader", _reader(world, "reader", "a", world.bugs))
    world.add_timeout("writer")


def _post_timeout(world: ChannelWorld) -> List[str]:
    # the zero-commit rewind retries the same seq: the reader must see
    # every frame exactly once whether or not the deadline fired
    return _check_reader(world, "reader", (1, 1), require_all=True)


def _build_dual(world: ChannelWorld) -> None:
    # MultiOutput / daemon-owned deposit shape: one writer committing
    # each frame to channel a THEN channel b (CompiledDAG.execute's
    # branch order), two independent readers, death sweep over both
    world.add_channel("a", capacity=2)
    world.add_channel("b", capacity=2)
    world.order_pairs.append(("b", "a"))
    world.add_actor("writer", _writer(world, "writer", ("a", "b"),
                                      (1, 1), world.bugs))
    world.add_actor("reader-a", _reader(world, "reader-a", "a",
                                        world.bugs))
    world.add_actor("reader-b", _reader(world, "reader-b", "b",
                                        world.bugs))
    world.add_kill("writer", spawn_poker_on=("a", "b"))


def _post_dual(world: ChannelWorld) -> List[str]:
    out = _check_reader(world, "reader-a", (1, 1), require_all=False)
    out += _check_reader(world, "reader-b", (1, 1), require_all=False)
    ga, gb = _got(world, "reader-a"), _got(world, "reader-b")
    if ga is not None and gb is not None and len(gb) > len(ga) + 1:
        out.append(
            f"reader-b consumed {gb} while reader-a consumed {ga}: "
            "channel b ran more than one frame ahead of a"
        )
    return out


CHANNEL_SCENARIOS: Dict[str, ChannelScenario] = {
    s.name: s for s in [
        ChannelScenario(
            "spsc-alternation",
            "writer/reader strict alternation over two frames of "
            "different sizes — every word-op interleaving",
            _build_spsc, _post_spsc,
        ),
        ChannelScenario(
            "writer-kill-midcommit",
            "writer killed at ANY op (crash consistency: old frame or "
            "CLOSED|ERROR, never torn) + daemon death-sweep poke",
            _build_kill, _post_kill,
        ),
        ChannelScenario(
            "grow-remap",
            "grow-in-place ftruncate+remap (frame larger than capacity) "
            "racing the reader's mapping re-check",
            _build_grow, _post_grow,
        ),
        ChannelScenario(
            "late-attach-grow",
            "a reader whose mapping predates a grow-in-place must remap "
            "before its first copy (open_wait attach-before-grow)",
            _build_late_attach_grow, _post_late_attach_grow,
        ),
        ChannelScenario(
            "close-vs-poke",
            "graceful CLOSED teardown racing a CLOSED|ERROR death poke "
            "against both (possibly parked) ends",
            _build_close_vs_poke, _post_close_vs_poke,
        ),
        ChannelScenario(
            "timeout-rewind",
            "write deadline expiry with zero frames committed: the "
            "CompiledDAG.execute seq rewind must keep frames aligned",
            _build_timeout, _post_timeout,
        ),
        ChannelScenario(
            "dual-reader-multioutput",
            "one writer, two channels (MultiOutput / daemon deposit), "
            "two readers, kill-at-any-op + sweep over both",
            _build_dual, _post_dual,
        ),
    ]
}


# -------------------------------------------------------------- results


@dataclasses.dataclass
class ChannelRunResult:
    scenario: str
    schedule: List[str]
    options_at: List[Tuple[str, ...]]
    keys_of: Dict[str, FrozenSet]
    violations: List[Violation]
    outcomes: Dict[str, List[tuple]]
    quiesced: bool
    crash_point: Optional[str]

    @property
    def violation_kinds(self) -> Set[str]:
        return {v.kind for v in self.violations}

    def schedule_log(self) -> str:
        return " | ".join(self.schedule)


def run_channel_world(scenario: ChannelScenario, chooser: Chooser,
                      seeded_bugs: Sequence[str] = (),
                      step_limit: int = 300) -> ChannelRunResult:
    """Execute one schedule of ``scenario`` from a fresh virtual
    channel; returns the schedule taken plus every violation (word-level
    invariants, torn/stale frames, deadlocks, unmet postconditions)."""
    world = ChannelWorld(chooser, bugs=seeded_bugs, step_limit=step_limit)
    scenario.build(world)
    world.run()
    quiesced = (
        not world.stopped_early
        and all(a.done for a in world.actors.values())
    )
    if quiesced and scenario.postcheck is not None:
        for msg in scenario.postcheck(world):
            world.violate("postcheck", msg)
    return ChannelRunResult(
        scenario=scenario.name,
        schedule=list(world.schedule),
        options_at=list(world.options_at),
        keys_of=dict(world.keys_of),
        violations=list(world.violations),
        outcomes=dict(world.outcomes),
        quiesced=quiesced,
        crash_point=world.crash_point,
    )


def _process_of(label: str) -> str:
    """Actor name of a step label ("writer.3:store:a.version" ->
    "writer"; extra steps like "kill:writer" are their own process)."""
    return label.split(":", 1)[0].split(".", 1)[0]


def _strip_counter(label: str) -> str:
    """Label without the per-actor op counter ("writer.3:store:a.version"
    -> "writer:store:a.version")."""
    head, _, rest = label.partition(":")
    return f"{_process_of(label)}:{rest}" if rest else head


class _LooseChooser(Chooser):
    """Chooser matching schedule entries by actor + op description,
    ignoring the per-actor op counters. Dropping a redundant spin-wait
    iteration from a counterexample renumbers every later op of that
    actor, so exact-label matching would refuse otherwise-valid shrink
    candidates. Unambiguous: each actor has exactly one pending op."""

    def choose(self, options, at_interleave):
        if self.i < len(self.prefix):
            want = _strip_counter(self.prefix[self.i])
            matches = [o for o in options if _strip_counter(o) == want]
            if not matches:
                raise ScheduleDiverged(
                    f"schedule step {self.i} wants {want!r}; enabled: "
                    f"{[_strip_counter(o) for o in options]}"
                )
            self.i += 1
            return matches[0]
        return super().choose(options, at_interleave)


def _actor_blocks(schedule: List[str]) -> List[Tuple[int, int]]:
    """Maximal same-actor contiguous runs [s, e) of a schedule — the
    removable units a per-actor-counter label scheme allows (e.g. one
    whole wait-loop iteration ending in a park)."""
    out: List[Tuple[int, int]] = []
    s = 0
    for i in range(1, len(schedule) + 1):
        if i == len(schedule) or \
                _process_of(schedule[i]) != _process_of(schedule[s]):
            out.append((s, i))
            s = i
    return out


def _mem_conflicts(a: FrozenSet, b: FrozenSet) -> bool:
    """Read/write-aware conflict relation over op keys: two accesses of
    the same (chan, word) conflict only if at least one writes — two
    loads commute, so the DFS never branches on their order."""
    if GLOBAL_KEY in a or GLOBAL_KEY in b:
        return True
    for ka in a:
        for kb in b:
            if ka[0] == "actor" or kb[0] == "actor":
                if ka == kb:
                    return True
                continue
            if ka[1:] == kb[1:] and "w" in (ka[0], kb[0]):
                return True
    return False


@dataclasses.dataclass
class ChannelExploreResult:
    scenario: str
    schedules_run: int
    dfs_schedules: int
    sampled_schedules: int
    branches_pruned: int
    branches_queued: int
    ops_covered: int
    crash_points: Set[str]
    elapsed_s: float
    violating: Optional[ChannelRunResult] = None
    shrunk: Optional[List[str]] = None
    shrunk_violations: Optional[List[Violation]] = None
    shrunk_stop_after: bool = True

    @property
    def found(self) -> bool:
        return self.violating is not None

    def summary(self) -> str:
        head = (
            f"{self.scenario}: {self.schedules_run} schedules "
            f"({self.dfs_schedules} dfs + {self.sampled_schedules} "
            f"sampled), {self.branches_pruned} branches pruned, "
            f"{self.ops_covered} ops, "
            f"{len(self.crash_points)} crash points, "
            f"{self.elapsed_s:.2f}s"
        )
        if not self.found:
            return head + " — no violations"
        kinds = sorted({v.kind for v in self.violating.violations})
        n = len(self.shrunk or self.violating.schedule)
        return head + f" — VIOLATION {kinds}, shrunk to {n} ops"


def explore_channel(
    scenario: ChannelScenario,
    max_schedules: int = 400,
    max_depth: Optional[int] = 40,
    samples: int = 100,
    seed: int = 0,
    seeded_bugs: Sequence[str] = (),
    wall_cap_s: Optional[float] = None,
    shrink: bool = True,
    step_limit: int = 300,
) -> ChannelExploreResult:
    """DFS + random-sampling exploration of one channel scenario via the
    shared explore.py engine; rw-aware conflict pruning. Stops at the
    first violating schedule (shrinking it to a minimal replay)."""
    t0 = _time.monotonic()
    ops_covered = 0
    crash_points: Set[str] = set()

    def run_fn(chooser: Chooser) -> ChannelRunResult:
        return run_channel_world(
            scenario, chooser, seeded_bugs=seeded_bugs,
            step_limit=step_limit,
        )

    def on_result(res: ChannelRunResult) -> None:
        nonlocal ops_covered
        ops_covered += len(res.schedule)
        if res.crash_point is not None:
            crash_points.add(res.crash_point)

    stats = dfs_explore(
        run_fn,
        max_schedules=max_schedules,
        max_depth=max_depth,
        samples=samples,
        seed=seed,
        wall_cap_s=wall_cap_s,
        conflicts=_mem_conflicts,
        process_of=_process_of,
        on_result=on_result,
    )
    violating = stats.violating
    result = ChannelExploreResult(
        scenario=scenario.name,
        schedules_run=stats.dfs_runs + stats.sampled_runs,
        dfs_schedules=stats.dfs_runs,
        sampled_schedules=stats.sampled_runs,
        branches_pruned=stats.pruned,
        branches_queued=stats.queued,
        ops_covered=ops_covered,
        crash_points=crash_points,
        elapsed_s=_time.monotonic() - t0,
        violating=violating,
    )
    if violating is not None and shrink:
        kinds = violating.violation_kinds
        # postcheck/deadlock violations only exist at quiescence: shrink
        # those with the default tail instead of truncation
        stop_after = not (kinds & {"postcheck", "deadlock"})
        shrunk, viol = shrink_generic(
            run_fn, violating.schedule, kinds, stop_after,
            chooser_factory=lambda prefix, stop: _LooseChooser(
                prefix, stop_after=stop
            ),
            blocks_of=_actor_blocks,
        )
        result.shrunk = shrunk
        result.shrunk_violations = viol
        result.shrunk_stop_after = stop_after
    return result


def explore_all_channels(
    names: Optional[Sequence[str]] = None, **kw
) -> Dict[str, ChannelExploreResult]:
    out: Dict[str, ChannelExploreResult] = {}
    for name in names or sorted(CHANNEL_SCENARIOS):
        out[name] = explore_channel(CHANNEL_SCENARIOS[name], **kw)
    return out


# --------------------------------------------------------------- replay


def write_channel_replay(path: str, result: ChannelExploreResult,
                         seeded_bugs: Sequence[str] = ()) -> None:
    assert result.violating is not None, "nothing to replay"
    schedule = result.shrunk or result.violating.schedule
    viols = (
        result.shrunk_violations
        if result.shrunk is not None
        else result.violating.violations
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "kind": "memmodel",
            "scenario": result.scenario,
            "seeded_bugs": sorted(seeded_bugs),
            "stop_after": result.shrunk_stop_after,
            "schedule": schedule,
            "violation_kinds": sorted({v.kind for v in (viols or [])}),
            "violations": [v.format() for v in (viols or [])],
        }, f, indent=2)
        f.write("\n")


def replay_channel(path: str) -> ChannelRunResult:
    """Re-execute a recorded memmodel counterexample deterministically."""
    with open(path, "r", encoding="utf-8") as f:
        rec = json.load(f)
    if rec.get("kind") != "memmodel":
        raise ValueError(f"{path} is not a memmodel replay")
    scenario = CHANNEL_SCENARIOS.get(rec["scenario"])
    if scenario is None:
        raise ValueError(f"unknown channel scenario {rec['scenario']!r}")
    return run_channel_world(
        scenario,
        _LooseChooser(rec["schedule"],
                      stop_after=rec.get("stop_after", True)),
        seeded_bugs=rec.get("seeded_bugs", ()),
    )


# ------------------------------------------------- static round-trip
#
# AST-extract the op sequences of the real Channel.write/read/close and
# poke_error, in source order with loop/optional structure, and match
# them against DECLARED_SEQUENCES — the same load-bearing pattern as the
# METHOD_TABLE round-trip: the model checker above exercises the
# DECLARED tables, this gate pins the tables to the shipped code.

_OP_ATTRS = {
    "_get": "load", "load": "load",
    "_put": "store", "store": "store",
}
_PAYLOAD_ATTRS = {
    "write_payload": ("store", "payload"),
    "read_payload": ("load", "payload"),
    "grow": ("grow", ""),
    "remap": ("remap", ""),
}


# chan_word_of (analysis/core.py) is the ONE word-constant recognizer,
# shared with the chan-publication-order checker


def _test_mentions(node: ast.AST, ident: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == ident
        for n in ast.walk(node)
    )


def _seeded_branch_kind(test: ast.AST) -> Optional[str]:
    """For an ``if`` gated on SEEDED_BUGS: "in" (bug-injection body —
    skip it) or "not-in" (the body IS the unseeded path — keep it)."""
    if not _test_mentions(test, "SEEDED_BUGS"):
        return None
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        if isinstance(test.ops[0], ast.NotIn):
            return "not-in"
        if isinstance(test.ops[0], ast.In):
            return "in"
    return "in"  # unknown shape: treat as injected, skip


def extract_op_sequence(
    fn: ast.FunctionDef,
) -> List[Tuple[str, str, str]]:
    """The ordered (kind, target, flags) word-op sequence of one
    channel-protocol function, flags ∈ {"", "loop", "opt"}."""
    ops: List[Tuple[str, str, str]] = []

    def flags_str(loop: bool, opt: bool) -> str:
        if opt:
            return "opt"
        return "loop" if loop else ""

    def visit_expr(node: ast.AST, loop: bool, opt: bool) -> None:
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _OP_ATTRS and node.args:
                word = chan_word_of(node.args[0])
                if word is not None:
                    # a store's value expression evaluates first: any
                    # loads inside it precede the store itself
                    for arg in node.args[1:]:
                        visit_expr(arg, loop, opt)
                    ops.append((_OP_ATTRS[attr], word,
                                flags_str(loop, opt)))
                    return
            if attr in _PAYLOAD_ATTRS:
                for arg in node.args:
                    visit_expr(arg, loop, opt)
                kind, target = _PAYLOAD_ATTRS[attr]
                ops.append((kind, target, flags_str(loop, opt)))
                return
        for child in ast.iter_child_nodes(node):
            visit_expr(child, loop, opt)

    def visit_stmts(stmts: Sequence[ast.stmt], loop: bool,
                    opt: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.While):
                visit_expr(stmt.test, True, opt)
                visit_stmts(stmt.body, True, opt)
                visit_stmts(stmt.orelse, loop, True)
            elif isinstance(stmt, ast.For):
                visit_stmts(stmt.body, True, opt)
            elif isinstance(stmt, ast.If):
                seeded = _seeded_branch_kind(stmt.test)
                if seeded == "in":
                    visit_stmts(stmt.orelse, loop, opt)
                    continue
                if seeded == "not-in":
                    # the guarded body is the normal (unseeded) path
                    visit_stmts(stmt.body, loop, opt)
                    visit_stmts(stmt.orelse, loop, True)
                    continue
                if _test_mentions(stmt.test, "_CRASH_AT"):
                    continue  # chaos hook: no protocol ops inside
                visit_expr(stmt.test, loop, opt)
                visit_stmts(stmt.body, loop, True)
                visit_stmts(stmt.orelse, loop, True)
            elif isinstance(stmt, ast.Try):
                visit_stmts(stmt.body, loop, opt)
                for h in stmt.handlers:
                    visit_stmts(h.body, loop, True)
                visit_stmts(stmt.orelse, loop, opt)
                visit_stmts(stmt.finalbody, loop, opt)
            elif isinstance(stmt, ast.With):
                visit_stmts(stmt.body, loop, opt)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested defs run later, not here
            else:
                visit_expr(stmt, loop, opt)

    visit_stmts(fn.body, False, False)
    return ops


def channel_op_sequences(
    source: Optional[str] = None,
) -> Dict[str, List[Tuple[str, str, str]]]:
    """Extract the op sequences of Channel.write/read/close and
    poke_error from dag/channel.py (or ``source`` for tests)."""
    if source is None:
        from ray_tpu.dag import channel as _chan

        with open(_chan.__file__, "r", encoding="utf-8") as f:
            source = f.read()
    tree = ast.parse(textwrap.dedent(source))
    out: Dict[str, List[Tuple[str, str, str]]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Channel":
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and \
                        item.name in ("write", "read", "close"):
                    out[item.name] = extract_op_sequence(item)
        elif isinstance(node, ast.FunctionDef) and \
                node.name == "poke_error":
            out[node.name] = extract_op_sequence(node)
    return out


def verify_op_sequences(source: Optional[str] = None) -> List[str]:
    """Round-trip gate: the real channel code's extracted op sequences
    must equal DECLARED_SEQUENCES (and the header word names must cover
    the declared layout). Returns mismatch descriptions; empty = ok."""
    problems: List[str] = []
    try:
        from ray_tpu.dag.channel import HEADER_LAYOUT

        layout_names = tuple(name for name, _ in HEADER_LAYOUT)
        if layout_names != WORD_NAMES:
            problems.append(
                "memmodel WORD_NAMES disagree with channel.HEADER_LAYOUT: "
                f"{WORD_NAMES} vs {layout_names}"
            )
    except Exception as e:  # noqa: BLE001 - import trouble IS a finding
        problems.append(f"cannot import dag/channel.py layout: {e}")
    extracted = channel_op_sequences(source)
    for name, declared in DECLARED_SEQUENCES.items():
        got = extracted.get(name)
        if got is None:
            problems.append(f"channel.py has no function {name!r}")
            continue
        if tuple(got) != tuple(declared):
            problems.append(
                f"op sequence of {name}() diverged from the checked "
                f"model:\n  declared: {list(declared)}\n  extracted: "
                f"{got}"
            )
    return problems
