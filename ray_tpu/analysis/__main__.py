"""CLI: ``python -m ray_tpu.analysis <paths> [options]``.

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage/parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ray_tpu.analysis.core import (
    CHECKERS,
    analyze_paths,
    load_baseline,
    split_by_baseline,
    write_baseline,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="ray_tpu distributed-correctness linter",
    )
    p.add_argument("paths", nargs="*", default=["ray_tpu"],
                   help="files/directories to scan (default: ray_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON ratchet baseline; findings whose fingerprint "
                        "appears there are reported but don't fail")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite --baseline with the current findings")
    p.add_argument("--select", default=None, metavar="CHECKS",
                   help="comma-separated subset of checks to run")
    p.add_argument("--list-checks", action="store_true")
    p.add_argument("--dump-rpcflow", action="store_true",
                   help="print the per-operation RPC cost table "
                        "(interprocedural call-graph multiplicity "
                        "analysis, analysis/rpcflow.py) and exit")
    p.add_argument("--dump-protocol", action="store_true",
                   help="instead of linting, emit the extracted RPC "
                        "protocol model (handlers, call sites, push/"
                        "subscribe topics, config knobs) as JSON")
    p.add_argument("--check-trace", default=None, metavar="TRACE",
                   help="instead of linting, replay a protocol trace "
                        "(JSONL from the invariant sanitizer) and verify "
                        "the happens-before invariants; exit 1 on "
                        "violations")
    p.add_argument("--explore", default=None, metavar="SCENARIO",
                   nargs="?", const="all",
                   help="instead of linting, model-check the control "
                        "plane: explore handler interleavings of one "
                        "scenario (or 'all') through the invariant "
                        "checker; exit 1 on any violation")
    p.add_argument("--memmodel", default=None, metavar="SCENARIO",
                   nargs="?", const="all",
                   help="instead of linting, model-check the compiled-"
                        "dag seqlock channel at word-op granularity: "
                        "explore writer/reader/poker interleavings of "
                        "one channel scenario (or 'all'), kill-at-any-op "
                        "included; exit 1 on any violation")
    p.add_argument("--race", default=None, metavar="PROBE",
                   nargs="?", const="all",
                   help="instead of linting, run the happens-before "
                        "race sanitizer's probe(s) (analysis/racer.py): "
                        "one probe (or 'all') drives real control-plane "
                        "code paths on controlled threads under the "
                        "vector-clock engine; exit 1 on any detected "
                        "race (--seed-bug re-introduces a known bug the "
                        "probe must then catch)")
    p.add_argument("--wait", default=None, metavar="PROBE",
                   nargs="?", const="all",
                   help="instead of linting, run the wait-graph "
                        "deadlock sanitizer's probe(s) "
                        "(analysis/waitgraph.py): one probe (or 'all') "
                        "drives real control-plane code paths on "
                        "controlled threads under the live wait-for "
                        "graph; exit 1 on any deadlock report "
                        "(--seed-bug re-introduces a known blocking "
                        "bug the probe must then catch)")
    p.add_argument("--rounds", type=int, default=3,
                   help="quiescence rounds per race/wait probe "
                        "(default 3)")
    p.add_argument("--dump-waitgraph", action="store_true",
                   help="instead of linting, emit the STATIC blocking "
                        "graph as JSON: (context, blocking-site) nodes "
                        "over cluster//serve//dag/, cross-process RPC "
                        "edges resolved through the protocol index, "
                        "and any blocking cycles found over them")
    p.add_argument("--dump-watchlist", action="store_true",
                   help="instead of linting, emit the race sanitizer's "
                        "STAGE-1 static watchlist as JSON: every "
                        "container/scalar field reachable from >= 2 "
                        "execution contexts in cluster//serve//dag/, "
                        "with the lock attrs the static pass credits "
                        "(validated dynamically by --race)")
    p.add_argument("--list-scenarios", action="store_true",
                   help="list every model-checking/sanitizer scenario, "
                        "kind-prefixed: control-plane interleaving "
                        "scenarios (--explore NAME), 'memmodel:NAME' "
                        "channel scenarios (--memmodel NAME), "
                        "'racer:NAME' race probes (--race NAME), and "
                        "'waitgraph:NAME' deadlock probes (--wait "
                        "NAME)")
    p.add_argument("--budget", type=int, default=500,
                   help="DFS schedule budget per scenario (default 500)")
    p.add_argument("--samples", type=int, default=200,
                   help="seeded-random schedules beyond the DFS bound "
                        "(default 200)")
    p.add_argument("--depth", type=int, default=30,
                   help="DFS branch-depth bound (default 30)")
    p.add_argument("--seed", type=int, default=0,
                   help="random-sampling seed (same seed = byte-"
                        "identical exploration)")
    p.add_argument("--wall-cap", type=float, default=None, metavar="S",
                   help="wall-clock cap in seconds per scenario")
    p.add_argument("--seed-bug", action="append", default=[],
                   metavar="NAME",
                   help="re-introduce a known fixed bug (gcs.SEEDED_BUGS "
                        "for --explore, channel.SEEDED_BUGS for "
                        "--memmodel, node_daemon/fastpath SEEDED_BUGS "
                        "for --race, gcs/compiled SEEDED_BUGS for "
                        "--wait) — the regression harness")
    p.add_argument("--save-replay", default=None, metavar="FILE",
                   help="write the first (shrunk) counterexample here")
    p.add_argument("--replay", default=None, metavar="FILE",
                   help="re-execute a recorded counterexample "
                        "deterministically; exit 1 if it still "
                        "violates. Kind-dispatched on the file's "
                        "'kind' field: 'memmodel' replays through the "
                        "channel model (analysis/memmodel.py), "
                        "anything else through the control-plane "
                        "explorer (analysis/explore.py); race-sanitizer "
                        "artifacts (kind 'race-report') are reports, "
                        "not replays, and are rejected with exit 2")
    args = p.parse_args(argv)

    # Import for side effect: populate the registry before --list-checks.
    from ray_tpu.analysis import checkers as _checkers  # noqa: F401

    if args.list_checks:
        for name in sorted(CHECKERS):
            print(f"{name}: {CHECKERS[name].description}")
        return 0

    if args.list_scenarios:
        from ray_tpu.analysis.explore import SCENARIOS
        from ray_tpu.analysis.memmodel import CHANNEL_SCENARIOS
        from ray_tpu.analysis.racer import RACE_PROBES

        for name in sorted(SCENARIOS):
            print(f"{name}: {SCENARIOS[name].description}")
        for name in sorted(CHANNEL_SCENARIOS):
            print(f"memmodel:{name}: "
                  f"{CHANNEL_SCENARIOS[name].description}")
        for name in sorted(RACE_PROBES):
            doc = (RACE_PROBES[name].__doc__ or "").split("\n")[0].strip()
            print(f"racer:{name}: {doc}")
        from ray_tpu.analysis.waitgraph import WAIT_PROBES

        for name in sorted(WAIT_PROBES):
            doc = (WAIT_PROBES[name].__doc__ or "").split("\n")[0].strip()
            print(f"waitgraph:{name}: {doc}")
        return 0

    if args.replay is not None:
        # memmodel replays carry "kind": "memmodel"; explore replays
        # predate the field — dispatch on it
        try:
            with open(args.replay, "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not isinstance(rec, dict):
            print(f"error: {args.replay} is not a replay object",
                  file=sys.stderr)
            return 2
        kind = rec.get("kind")
        if kind == "race-report":
            print("error: race-sanitizer artifacts are reports, not "
                  "replays (the racer re-detects from the live probes: "
                  "--race)", file=sys.stderr)
            return 2
        try:
            if kind == "memmodel":
                from ray_tpu.analysis import memmodel as _memmodel

                res = _memmodel.replay_channel(args.replay)
            else:
                from ray_tpu.analysis import explore as _explore

                res = _explore.replay(args.replay)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"replayed {len(res.schedule)} steps of {res.scenario}:")
        for step in res.schedule:
            print(f"  {step}")
        for v in res.violations:
            print(v.format())
        print(f"{len(res.violations)} violation(s)")
        return 1 if res.violations else 0

    if args.dump_watchlist:
        from ray_tpu.analysis.racer import extract_watchlist

        paths = None
        if args.paths and args.paths != ["ray_tpu"]:
            missing = [p_ for p_ in args.paths if not os.path.exists(p_)]
            if missing:
                print(f"error: no such path(s): {missing}",
                      file=sys.stderr)
                return 2
            paths = args.paths
        try:
            wl = extract_watchlist(paths=paths)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(wl, indent=2))
        return 0

    if args.race is not None:
        from ray_tpu.analysis import racer as _racer

        requested = args.race.split("racer:", 1)[-1]
        names = (
            sorted(_racer.RACE_PROBES) if requested == "all"
            else [requested]
        )
        unknown = [n for n in names if n not in _racer.RACE_PROBES]
        if unknown:
            print(f"error: unknown race probe(s) {unknown}; have "
                  f"{sorted(_racer.RACE_PROBES)}", file=sys.stderr)
            return 2
        failed = False
        wl = _racer.extract_watchlist()
        for name in names:
            try:
                res = _racer.run_probe(
                    name, seeded_bugs=args.seed_bug, rounds=args.rounds,
                    watchlist=wl,
                )
            except ValueError as e:  # unknown --seed-bug name
                print(f"error: {e}", file=sys.stderr)
                return 2
            print(res.summary())
            if res.unresolved:
                failed = True
                for e, err in res.unresolved:
                    print(f"  unresolved watchlist entry "
                          f"{e['cls']}.{e['field']}: {err}",
                          file=sys.stderr)
            if res.detected:
                failed = True
                for r in res.races:
                    print(f"  RACE {r['kind']} on {r['field']} "
                          f"(static locked={r['static']['locked']})")
                    for side in ("prior", "current"):
                        a = r[side]
                        print(f"    {side}: {a.get('thread')} "
                              f"locks={a.get('locks')}")
                        for fr in a.get("stack", ())[:3]:
                            print(f"      {fr}")
        return 1 if failed else 0

    if args.dump_waitgraph:
        from ray_tpu.analysis import waitgraph as _wg

        paths = None
        if args.paths and args.paths != ["ray_tpu"]:
            missing = [p_ for p_ in args.paths if not os.path.exists(p_)]
            if missing:
                print(f"error: no such path(s): {missing}",
                      file=sys.stderr)
                return 2
            paths = args.paths
        try:
            report = _wg.build_waitgraph(paths=paths)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(report.to_dict(), indent=2))
        return 1 if report.cycles else 0

    if args.wait is not None:
        from ray_tpu.analysis import waitgraph as _wg

        # accept the "waitgraph:NAME" spelling --list-scenarios prints
        requested = args.wait.split("waitgraph:", 1)[-1]
        names = (
            sorted(_wg.WAIT_PROBES) if requested == "all"
            else [requested]
        )
        unknown = [n for n in names if n not in _wg.WAIT_PROBES]
        if unknown:
            print(f"error: unknown wait probe(s) {unknown}; have "
                  f"{sorted(_wg.WAIT_PROBES)}", file=sys.stderr)
            return 2
        failed = False
        for name in names:
            try:
                res = _wg.run_probe(
                    name, seeded_bugs=args.seed_bug, rounds=args.rounds,
                )
            except ValueError as e:  # unknown --seed-bug name
                print(f"error: {e}", file=sys.stderr)
                return 2
            print(res.summary())
            if res.detected:
                failed = True
                for d in res.deadlocks:
                    print(f"  DEADLOCK cycle: "
                          f"{' -> '.join(d.get('cycle', ()))}")
                    for t in d.get("threads", ()):
                        print(f"    {t.get('thread')} waiting on "
                              f"{t.get('waiting_on')} "
                              f"held={t.get('held')}")
                        for fr in (t.get("stack") or ())[-3:]:
                            print(f"      {fr}")
                    for hop in d.get("rpc_chain", ()):
                        print(f"    rpc hop: {hop}")
        return 1 if failed else 0

    if args.memmodel is not None:
        from ray_tpu.analysis import memmodel as _memmodel

        # accept the "memmodel:NAME" spelling --list-scenarios prints
        requested = args.memmodel.split("memmodel:", 1)[-1]
        names = (
            sorted(_memmodel.CHANNEL_SCENARIOS) if requested == "all"
            else [requested]
        )
        unknown = [n for n in names
                   if n not in _memmodel.CHANNEL_SCENARIOS]
        if unknown:
            print(f"error: unknown channel scenario(s) {unknown}; have "
                  f"{sorted(_memmodel.CHANNEL_SCENARIOS)}",
                  file=sys.stderr)
            return 2
        problems = _memmodel.verify_op_sequences()
        for msg in problems:
            print(f"round-trip: {msg}", file=sys.stderr)
        failed = bool(problems)
        for name in names:
            res = _memmodel.explore_channel(
                _memmodel.CHANNEL_SCENARIOS[name],
                max_schedules=args.budget,
                samples=args.samples,
                max_depth=args.depth,
                seed=args.seed,
                seeded_bugs=args.seed_bug,
                wall_cap_s=args.wall_cap,
            )
            print(res.summary())
            if res.found:
                failed = True
                for v in (res.shrunk_violations
                          or res.violating.violations):
                    print("  " + v.format())
                print("  minimal schedule:")
                for step in (res.shrunk or res.violating.schedule):
                    print(f"    {step}")
                if args.save_replay:
                    _memmodel.write_channel_replay(
                        args.save_replay, res, seeded_bugs=args.seed_bug
                    )
                    print(f"  replay written to {args.save_replay} "
                          "(re-run with --replay)")
        return 1 if failed else 0

    if args.explore is not None:
        from ray_tpu.analysis import explore as _explore

        names = (
            sorted(_explore.SCENARIOS) if args.explore == "all"
            else [args.explore]
        )
        unknown = [n for n in names if n not in _explore.SCENARIOS]
        if unknown:
            print(f"error: unknown scenario(s) {unknown}; have "
                  f"{sorted(_explore.SCENARIOS)}", file=sys.stderr)
            return 2
        failed = False
        for name in names:
            res = _explore.explore(
                _explore.SCENARIOS[name],
                max_schedules=args.budget,
                samples=args.samples,
                max_depth=args.depth,
                seed=args.seed,
                seeded_bugs=args.seed_bug,
                wall_cap_s=args.wall_cap,
            )
            print(res.summary())
            if res.found:
                failed = True
                for v in (res.shrunk_violations
                          or res.violating.violations):
                    print("  " + v.format())
                print("  minimal schedule:")
                for step in (res.shrunk or res.violating.schedule):
                    print(f"    {step}")
                if args.save_replay:
                    _explore.write_replay(
                        args.save_replay, res, seeded_bugs=args.seed_bug
                    )
                    print(f"  replay written to {args.save_replay} "
                          "(re-run with --replay)")
        return 1 if failed else 0

    if args.check_trace is not None:
        from ray_tpu.analysis.invariants import check_trace

        try:
            violations = check_trace(args.check_trace)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        for v in violations:
            print(v.format())
        print(f"{len(violations)} invariant violation(s)")
        return 1 if violations else 0

    if args.dump_rpcflow:
        from ray_tpu.analysis.rpcflow import build_rpcflow, format_rpcflow

        paths = [p_ for p_ in args.paths if os.path.exists(p_)]
        missing = [p_ for p_ in args.paths if not os.path.exists(p_)]
        if missing or not paths:
            print(f"error: no such path(s): {missing}", file=sys.stderr)
            return 2
        report = build_rpcflow(paths, root=os.getcwd())
        if args.format == "json":
            print(json.dumps(report.to_dict(), indent=2))
        else:
            print(format_rpcflow(report))
        return 2 if report.unresolved_entries else 0

    if args.dump_protocol:
        from ray_tpu.analysis.protocol import extract_protocol

        paths = [p_ for p_ in args.paths if os.path.exists(p_)]
        missing = [p_ for p_ in args.paths if not os.path.exists(p_)]
        if missing or not paths:
            print(f"error: no such path(s): {missing}", file=sys.stderr)
            return 2
        try:
            idx = extract_protocol(paths)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(idx.to_dict(), indent=2))
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    if select and args.update_baseline:
        # A partial-check scan would rewrite the baseline without the
        # unselected checks' entries, re-firing them as "new" later.
        print("error: --update-baseline cannot be combined with --select",
              file=sys.stderr)
        return 2
    paths = [p_ for p_ in args.paths if os.path.exists(p_)]
    missing = [p_ for p_ in args.paths if not os.path.exists(p_)]
    if missing or not paths:
        print(f"error: no such path(s): {missing}", file=sys.stderr)
        return 2
    # Fingerprints hash Finding.path, so anchor relpaths to the baseline
    # file's directory: the baseline then works from any cwd.
    root = (
        os.path.dirname(os.path.abspath(args.baseline))
        if args.baseline
        else None
    )
    try:
        result = analyze_paths(paths, root=root, select=select)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        if not args.baseline:
            print("error: --update-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        if result.errors:
            # Refuse to write a baseline from a partial scan: findings in
            # the unparseable files would later surface as "new".
            for e in result.errors:
                print(f"parse error: {e}", file=sys.stderr)
            print("error: not updating baseline from a partial scan",
                  file=sys.stderr)
            return 2
        write_baseline(args.baseline, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to {args.baseline}")
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, known = split_by_baseline(result.findings, baseline)

    if args.format == "json":
        print(json.dumps(
            {
                "new": [f.to_dict() for f in new],
                "baselined": [f.to_dict() for f in known],
                "suppressed": result.suppressed,
                "files_scanned": result.files_scanned,
                "errors": result.errors,
                "checks": sorted(select or CHECKERS),
            },
            indent=2,
        ))
    else:
        for f in new:
            print(f.format())
        for f in known:
            print(f"{f.format()}  (baselined)")
        for e in result.errors:
            print(f"parse error: {e}", file=sys.stderr)
        print(
            f"{result.files_scanned} file(s) scanned: {len(new)} new, "
            f"{len(known)} baselined, {result.suppressed} suppressed"
        )
    if result.errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
