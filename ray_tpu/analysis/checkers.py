"""Distributed-correctness checkers (the plugin table).

Each checker is a small AST visitor registered via ``@register``:

- ``blocking-in-async``     blocking calls on an asyncio event loop
- ``unsafe-closure-capture`` remote closures capturing unserializable state
- ``lock-order-cycle``      cycles in the static lock-acquisition graph
- ``unawaited-coroutine``   coroutine created and never awaited
- ``dropped-object-ref``    ``.remote()`` result discarded (lost task/error)
- ``resource-spec-validation`` task/actor resource requests the scheduler
                            layer can never satisfy

The lock graph and resource-name registry are whole-program: they
accumulate across ``check_module`` calls and report from ``finalize``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleContext,
    chan_word_of,
    find_cycles,
    register,
)

# ------------------------------------------------------------------- utilities


class ImportMap:
    """alias -> canonical dotted prefix, from a module's import statements."""

    def __init__(self, tree: ast.AST):
        self.modules: Dict[str, str] = {}  # "np" -> "numpy"
        self.names: Dict[str, str] = {}  # "sleep" -> "time.sleep"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.modules[a.asname] = a.name
                    else:
                        # `import a.b` binds only `a`, and an attribute
                        # chain through it already spells the full dotted
                        # path — mapping `a -> a.b` would double-expand
                        # (`concurrent.futures.futures.…`).
                        top = a.name.split(".")[0]
                        self.modules[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.names[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name for a Name/Attribute chain, resolving
        top-level import aliases; None for non-name expressions."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        base = cur.id
        parts.append(base)
        parts.reverse()
        if base in self.names:
            parts[0:1] = self.names[base].split(".")
        elif base in self.modules:
            parts[0:1] = self.modules[base].split(".")
        return ".".join(parts)


def _is_remote_decorator(dec: ast.AST) -> bool:
    """Matches @remote, @ray_tpu.remote, @<alias>.remote, and the
    argument-taking forms @remote(...), @ray_tpu.remote(...)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Name):
        return dec.id == "remote"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "remote"
    return False


def _remote_decorator_calls(node) -> List[ast.Call]:
    return [
        d for d in getattr(node, "decorator_list", [])
        if isinstance(d, ast.Call) and _is_remote_decorator(d)
    ]


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# ------------------------------------------------------------ blocking-in-async

# Calls that block the calling OS thread; on an event loop they stall every
# other coroutine sharing that loop (reference: Ray's asyncio-actor docs ban
# exactly these inside async actor methods).
_BLOCKING_CALLS: Dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.call": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.check_call": "use an executor",
    "subprocess.check_output": "use an executor",
    "os.system": "use an executor",
    "socket.create_connection": "use `asyncio.open_connection`",
    "requests.get": "use an executor or async client",
    "requests.post": "use an executor or async client",
    "ray_tpu.get": "blocking driver API stalls the loop; "
    "use `asyncio.wrap_future`/an executor or restructure",
    "ray_tpu.wait": "blocking driver API stalls the loop; use an executor",
}

# Constructors whose instances have thread-blocking methods worth tracking
# when bound to locals inside the async function.
_BLOCKING_CTORS: Dict[str, Set[str]] = {
    "queue.Queue": {"get", "put", "join"},
    "queue.SimpleQueue": {"get", "put"},
    "threading.Lock": {"acquire"},
    "threading.RLock": {"acquire"},
    "threading.Event": {"wait"},
    "threading.Condition": {"wait", "acquire", "wait_for"},
    "threading.Semaphore": {"acquire"},
    "threading.Thread": {"join"},
}


def _walk_body(fn):
    """Yield nodes executing in fn's own frame: skips nested defs/lambdas
    (they run elsewhere, or are separate bodies visited on their own)."""

    def gen(node, top):
        if not top and isinstance(node, _FUNC_NODES + (ast.Lambda,)):
            return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from gen(child, False)

    yield from gen(fn, True)


def _class_lock_attrs(cls: ast.ClassDef, imports: "ImportMap") -> Dict[str, str]:
    """{attr: ctor} for `self.X = threading.Lock()/RLock()/Condition()`."""
    out: Dict[str, str] = {}
    for sub in ast.walk(cls):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            ctor = imports.resolve(sub.value.func)
            if ctor in _LOCK_CTORS:
                for tgt in sub.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        out[tgt.attr] = ctor
    return out


@register
class BlockingInAsyncChecker(Checker):
    name = "blocking-in-async"
    description = (
        "thread-blocking call on an event loop: inside an `async def` "
        "body, a sync function it (transitively) calls, or a sync method "
        "of an async actor (those run ON the actor's loop thread)"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        imports = ImportMap(ctx.tree)
        out: List[Finding] = []
        blockers = self._module_blockers(ctx.tree, imports)

        for node in ctx.tree.body:
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_on_loop_body(
                    node, imports, ctx, out, blockers, lock_attrs={},
                    cls=None,
                )
            elif isinstance(node, ast.ClassDef):
                lock_attrs = _class_lock_attrs(node, imports)
                has_async = any(
                    isinstance(m, ast.AsyncFunctionDef) for m in node.body
                )
                is_remote = any(
                    _is_remote_decorator(d) for d in node.decorator_list
                )
                # Methods handed to threading.Thread(target=self.X) run on
                # their own OS thread, not the actor loop — exempt from the
                # sync-method-on-loop rule.
                thread_targets: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        for kw in sub.keywords:
                            if (
                                kw.arg == "target"
                                and isinstance(kw.value, ast.Attribute)
                                and isinstance(kw.value.value, ast.Name)
                                and kw.value.value.id == "self"
                            ):
                                thread_targets.add(kw.value.attr)
                for m in node.body:
                    if isinstance(m, ast.AsyncFunctionDef):
                        self._check_on_loop_body(
                            m, imports, ctx, out, blockers, lock_attrs,
                            cls=node.name,
                        )
                    elif (
                        isinstance(m, ast.FunctionDef)
                        and has_async
                        and is_remote
                        and not m.name.startswith("__")
                        and m.name not in thread_targets
                    ):
                        # Async-actor contract: sync methods of an async
                        # actor execute ON the loop thread too.
                        self._check_on_loop_body(
                            m, imports, ctx, out, blockers, lock_attrs,
                            cls=node.name, sync_on_loop=True,
                        )
        # Nested async defs anywhere (e.g. inside sync helpers).
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef) and not any(
                node is t or (isinstance(t, ast.ClassDef) and node in t.body)
                for t in ctx.tree.body
            ):
                self._check_on_loop_body(
                    node, imports, ctx, out, blockers, lock_attrs={},
                    cls=None,
                )
        return out

    # -- transitive "does this sync function/method block?" summaries

    def _direct_reason(self, fn, imports) -> Optional[str]:
        for sub in _walk_body(fn):
            if isinstance(sub, ast.Call):
                dotted = imports.resolve(sub.func)
                if dotted in _BLOCKING_CALLS:
                    return f"calls `{dotted}` at line {sub.lineno}"
        return None

    def _module_blockers(self, tree, imports) -> Dict[Tuple, str]:
        """{(class or None, func name): reason} for sync defs that block,
        propagated through same-module/same-class sync call chains."""
        funcs: Dict[Tuple, ast.AST] = {}
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                funcs[(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, ast.FunctionDef):
                        funcs[(node.name, m.name)] = m
        reasons: Dict[Tuple, str] = {}
        for key, fn in funcs.items():
            r = self._direct_reason(fn, imports)
            if r:
                reasons[key] = r
        changed = True
        while changed:
            changed = False
            for key, fn in funcs.items():
                if key in reasons:
                    continue
                cls = key[0]
                for sub in _walk_body(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = None
                    f = sub.func
                    if isinstance(f, ast.Name) and (None, f.id) in reasons:
                        callee = (None, f.id)
                    elif (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and (cls, f.attr) in reasons
                    ):
                        callee = (cls, f.attr)
                    if callee:
                        reasons[key] = (
                            f"calls `{'.'.join(filter(None, callee))}` "
                            f"which {reasons[callee]}"
                        )
                        changed = True
                        break
        return reasons

    # -- per-body check

    def _check_on_loop_body(
        self, fn, imports, ctx, out, blockers, lock_attrs, cls,
        sync_on_loop=False,
    ):
        where = (
            f"`async def {fn.name}`"
            if not sync_on_loop
            else f"sync method `{fn.name}` of async actor `{cls}` "
            "(runs on the actor event loop)"
        )
        local_ctors: Dict[str, str] = {}
        awaited: Set[int] = set()
        for node in _walk_body(fn):
            if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
                awaited.add(id(node.value))
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = imports.resolve(node.value.func)
                if ctor in _BLOCKING_CTORS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_ctors[tgt.id] = ctor
            # threading lock/condition acquisition on the loop thread
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    e = item.context_expr
                    if (
                        isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr in lock_attrs
                    ):
                        out.append(
                            ctx.finding(
                                e,
                                self.name,
                                f"`with self.{e.attr}` "
                                f"({lock_attrs[e.attr]}) inside {where} "
                                "blocks the event loop when contended; "
                                "use asyncio primitives or confine the "
                                "state to the loop thread",
                            )
                        )
            if isinstance(node, ast.Call) and id(node) not in awaited:
                self._check_call(
                    node, imports, local_ctors, where, cls, blockers,
                    lock_attrs, ctx, out,
                )

    def _check_call(
        self, call, imports, local_ctors, where, cls, blockers, lock_attrs,
        ctx, out,
    ):
        dotted = imports.resolve(call.func)
        if dotted in _BLOCKING_CALLS:
            out.append(
                ctx.finding(
                    call,
                    self.name,
                    f"blocking call `{dotted}` inside {where}; "
                    f"{_BLOCKING_CALLS[dotted]}",
                )
            )
            return
        func = call.func
        if not isinstance(func, ast.Attribute):
            # sync same-module function that (transitively) blocks
            if isinstance(func, ast.Name) and (None, func.id) in blockers:
                out.append(
                    ctx.finding(
                        call,
                        self.name,
                        f"call to `{func.id}` inside {where} blocks: "
                        f"{blockers[(None, func.id)]}",
                    )
                )
            return
        # self.<m>() where m (transitively) blocks
        if (
            isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and (cls, func.attr) in blockers
        ):
            out.append(
                ctx.finding(
                    call,
                    self.name,
                    f"call to `self.{func.attr}` inside {where} blocks: "
                    f"{blockers[(cls, func.attr)]}",
                )
            )
            return
        # Unawaited concurrent.futures-style join.
        if func.attr == "result":
            out.append(
                ctx.finding(
                    call,
                    self.name,
                    f"un-awaited `.result()` inside {where} blocks the "
                    "event loop; await the future (or wrap with "
                    "`asyncio.wrap_future`)",
                )
            )
            return
        # `self._lock.acquire()` on a class threading lock.
        if (
            func.attr in ("acquire", "wait", "wait_for")
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
            and func.value.attr in lock_attrs
        ):
            out.append(
                ctx.finding(
                    call,
                    self.name,
                    f"blocking `self.{func.value.attr}.{func.attr}()` "
                    f"({lock_attrs[func.value.attr]}) inside {where}; use "
                    "asyncio primitives",
                )
            )
            return
        # Blocking method on a local bound to a known blocking ctor.
        if isinstance(func.value, ast.Name):
            ctor = local_ctors.get(func.value.id)
            if ctor and func.attr in _BLOCKING_CTORS[ctor]:
                out.append(
                    ctx.finding(
                        call,
                        self.name,
                        f"blocking `{func.value.id}.{func.attr}()` "
                        f"({ctor}) inside {where}; use the asyncio "
                        "equivalent",
                    )
                )


# ------------------------------------------------------ unsafe-closure-capture

# Constructors producing objects that cannot cross a serialization boundary
# (cloudpickle refuses locks/sockets/files; device arrays must travel via
# the object store, not closure bytes).
_UNSERIALIZABLE_CTORS: Dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "asyncio.Lock": "asyncio lock",
    "asyncio.Event": "asyncio event",
    "asyncio.Condition": "asyncio condition",
    "asyncio.Queue": "asyncio queue",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "open": "file handle",
    "concurrent.futures.ThreadPoolExecutor": "thread pool",
    "concurrent.futures.ProcessPoolExecutor": "process pool",
    "jax.device_put": "device array",
}


@register
class UnsafeClosureCaptureChecker(Checker):
    name = "unsafe-closure-capture"
    description = (
        "@remote task/actor closure captures an unserializable object "
        "(lock, socket, file handle, executor, device array) from an "
        "enclosing function scope"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        imports = ImportMap(ctx.tree)
        out: List[Finding] = []
        # Stack of enclosing-function binding maps: var -> ctor dotted name.
        scopes: List[Dict[str, str]] = []

        def visit(node):
            if isinstance(node, _FUNC_NODES):
                if scopes and any(
                    _is_remote_decorator(d) for d in node.decorator_list
                ):
                    self._check_remote_closure(node, scopes, ctx, out)
                # Own-frame bindings only: a sibling helper's local can
                # never be captured by this function's nested closures.
                bindings: Dict[str, str] = {}
                for sub in _walk_body(node):
                    if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call
                    ):
                        ctor = imports.resolve(sub.value.func)
                        if ctor in _UNSERIALIZABLE_CTORS:
                            for tgt in sub.targets:
                                if isinstance(tgt, ast.Name):
                                    bindings[tgt.id] = ctor
                scopes.append(bindings)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                scopes.pop()
            else:
                for child in ast.iter_child_nodes(node):
                    visit(child)

        visit(ctx.tree)
        return out

    def _check_remote_closure(self, fn, scopes, ctx, out):
        local: Set[str] = {a.arg for a in fn.args.args}
        local.update(a.arg for a in fn.args.kwonlyargs)
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        reported: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        local.add(tgt.id)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                name = sub.id
                if name in local or name in reported:
                    continue
                for bindings in reversed(scopes):
                    ctor = bindings.get(name)
                    if ctor:
                        reported.add(name)
                        out.append(
                            ctx.finding(
                                sub,
                                self.name,
                                f"remote closure `{fn.name}` captures "
                                f"`{name}` "
                                f"({_UNSERIALIZABLE_CTORS[ctor]} from "
                                f"`{ctor}`), which cannot serialize to a "
                                "worker; pass state via args/ObjectRefs "
                                "or create it inside the task",
                            )
                        )
                        break


# ------------------------------------------------------------- lock-order-cycle

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}


@register
class LockOrderCycleChecker(Checker):
    name = "lock-order-cycle"
    description = (
        "cycle in the static lock-acquisition graph (`with a: with b:` in "
        "one code path, `with b: with a:` in another)"
    )

    def __init__(self):
        # node -> {"kind": Lock|RLock|Condition, "where": (path, line)}
        self.nodes: Dict[str, Dict] = {}
        # (src, dst) -> (path, line) of the inner acquisition
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # Plain-Lock self-nesting is an immediate deadlock, found per-module.
        self._module_findings: List[Finding] = []

    # -- module pass: collect lock nodes, then acquisition orderings

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        imports = ImportMap(ctx.tree)
        class_locks = self._collect_locks(ctx, imports)
        self._module_findings = []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self._walk_class(node, class_locks.get(node.name, {}), ctx)
            elif isinstance(node, _FUNC_NODES):
                self._walk_function(
                    node, owner=None, locks=class_locks.get(None, {}),
                    summaries={}, ctx=ctx,
                )
        return self._module_findings

    def _collect_locks(self, ctx, imports) -> Dict[Optional[str], Dict[str, str]]:
        """{class name (None = module level): {attr/var: node name}}."""
        locks: Dict[Optional[str], Dict[str, str]] = {None: {}}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = imports.resolve(node.value.func)
                if ctor in _LOCK_CTORS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            nid = f"{ctx.modname}.{tgt.id}"
                            locks[None][tgt.id] = nid
                            self.nodes[nid] = {
                                "kind": _LOCK_CTORS[ctor],
                                "where": (ctx.relpath, node.lineno),
                            }
            elif isinstance(node, ast.ClassDef):
                attrs: Dict[str, str] = {}
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call
                    ):
                        ctor = imports.resolve(sub.value.func)
                        if ctor not in _LOCK_CTORS:
                            continue
                        for tgt in sub.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                nid = f"{ctx.modname}.{node.name}.{tgt.attr}"
                                attrs[tgt.attr] = nid
                                self.nodes[nid] = {
                                    "kind": _LOCK_CTORS[ctor],
                                    "where": (ctx.relpath, sub.lineno),
                                }
                locks[node.name] = attrs
        return locks

    def _walk_class(self, cls: ast.ClassDef, locks: Dict[str, str], ctx):
        # Method summaries: locks a method acquires anywhere inside, to
        # propagate one interprocedural level (self.m() under a held lock).
        methods = [n for n in cls.body if isinstance(n, _FUNC_NODES)]
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for m in methods:
            acq: Set[str] = set()
            called: Set[str] = set()
            for sub in ast.walk(m):
                # Only true acquisitions count toward a method's summary:
                # `with <lock>` items and bare `.acquire()` calls, not any
                # mention of the attribute.
                if isinstance(sub, ast.withitem):
                    nid = self._lock_of(sub.context_expr, locks)
                    if nid:
                        acq.add(nid)
                elif isinstance(sub, ast.Call):
                    nid = self._lock_of(sub, locks)
                    if nid:
                        acq.add(nid)
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"
                ):
                    called.add(sub.func.attr)
            direct[m.name] = acq
            calls[m.name] = called
        # Fixpoint: summary = direct ∪ summaries of self-calls.
        summaries = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for c in callees:
                    extra = summaries.get(c, set()) - summaries[m]
                    if extra:
                        summaries[m].update(extra)
                        changed = True
        for m in methods:
            self._walk_function(m, cls.name, locks, summaries, ctx)

    def _lock_of(self, node, locks: Dict[str, str]) -> Optional[str]:
        """Lock node for `with self._x` / `with mod_lock` context exprs and
        bare `.acquire()` calls."""
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "acquire":
                node = f.value
            else:
                return None
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return locks.get(node.attr)
        if isinstance(node, ast.Name):
            return locks.get(node.id)
        return None

    def _walk_function(self, fn, owner, locks, summaries, ctx):
        held: List[Tuple[str, int]] = []  # (node, lineno acquired)

        def add_edges(dst: str, lineno: int):
            for src, _ in held:
                if src == dst:
                    kind = self.nodes.get(src, {}).get("kind")
                    if kind == "Lock":
                        self._module_findings.append(
                            Finding(
                                path=ctx.relpath,
                                line=lineno,
                                col=0,
                                check=self.name,
                                message=(
                                    f"nested re-acquisition of plain Lock "
                                    f"`{src}` — self-deadlock (use RLock "
                                    "or restructure)"
                                ),
                                line_text=ctx.line_text(lineno),
                            )
                        )
                    continue
                key = (src, dst)
                if key not in self.edges:
                    self.edges[key] = (ctx.relpath, lineno)

        def walk(node, top=False):
            if not top and isinstance(node, _FUNC_NODES + (ast.Lambda,)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    nid = self._lock_of(item.context_expr, locks)
                    if nid:
                        add_edges(nid, item.context_expr.lineno)
                        held.append((nid, item.context_expr.lineno))
                        acquired.append(nid)
                for stmt in node.body:
                    walk(stmt)
                for _ in acquired:
                    held.pop()
                return
            if (
                held
                and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            ):
                for dst in summaries.get(node.func.attr, ()):  # interproc edge
                    add_edges(dst, node.lineno)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(fn, top=True)

    # -- whole-program pass: cycle detection over the accumulated graph

    def finalize(self) -> List[Finding]:
        # Shared cycle enumeration (core.find_cycles) keeps this and the
        # runtime sanitizer agreeing on what counts as a cycle; add_edges
        # never inserts self-edges, so no self-loop guard is needed here.
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        return [self._cycle_finding(path) for path in find_cycles(adj)]

    def _cycle_finding(self, path: List[str]) -> Finding:
        hops = []
        for i, src in enumerate(path):
            dst = path[(i + 1) % len(path)]
            where = self.edges.get((src, dst))
            loc = f"{where[0]}:{where[1]}" if where else "?"
            hops.append(f"{src} -> {dst} ({loc})")
        first = self.edges.get((path[0], path[1 % len(path)]), ("?", 1))
        return Finding(
            path=first[0],
            line=first[1],
            col=0,
            check=self.name,
            message="lock-order cycle: " + "; ".join(hops),
            line_text="",
        )


# ---------------------------------------------------------- unawaited-coroutine


@register
class UnawaitedCoroutineChecker(Checker):
    name = "unawaited-coroutine"
    description = (
        "call to a locally-defined `async def` whose coroutine is never "
        "awaited/scheduled (the body silently never runs)"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        module_async: Set[str] = {
            n.name
            for n in ctx.tree.body
            if isinstance(n, ast.AsyncFunctionDef)
        }
        out: List[Finding] = []

        def visit(node, class_async: Set[str], local_async: Set[str]):
            if isinstance(node, ast.ClassDef):
                methods = {
                    m.name
                    for m in node.body
                    if isinstance(m, ast.AsyncFunctionDef)
                }
                for child in ast.iter_child_nodes(node):
                    visit(child, methods, local_async)
                return
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
                f = call.func
                name = None
                if isinstance(f, ast.Name) and (
                    f.id in module_async or f.id in local_async
                ):
                    name = f.id
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in class_async
                ):
                    name = f"self.{f.attr}"
                if name:
                    out.append(
                        ctx.finding(
                            call,
                            self.name,
                            f"coroutine `{name}(...)` is created but never "
                            "awaited — the body never runs; `await` it or "
                            "schedule with `asyncio.create_task`/"
                            "`run_coroutine_threadsafe`",
                        )
                    )
            if isinstance(node, _FUNC_NODES):
                # A nested async def is only callable bare inside its
                # definer — scope it to this function's subtree, so an
                # unrelated same-named sync function elsewhere in the
                # module is never flagged. Collect defs anywhere in this
                # function's own frame (if/try/for blocks included) via
                # _walk_body, which stops at deeper function boundaries.
                nested = {
                    sub.name
                    for frame_node in _walk_body(node)
                    for sub in ast.iter_child_nodes(frame_node)
                    if isinstance(sub, ast.AsyncFunctionDef)
                }
                for child in ast.iter_child_nodes(node):
                    visit(child, class_async, local_async | nested)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, class_async, local_async)

        visit(ctx.tree, set(), set())
        return out


# ----------------------------------------------------------- dropped-object-ref


@register
class DroppedObjectRefChecker(Checker):
    name = "dropped-object-ref"
    description = (
        "`.remote()` result discarded: task errors and completion are "
        "unobservable, and the ref cannot be cancelled or fetched"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            if isinstance(f, ast.Attribute) and f.attr == "remote":
                out.append(
                    ctx.finding(
                        node.value,
                        self.name,
                        "ObjectRef from `.remote(...)` is dropped — task "
                        "failures vanish silently; store/fetch the ref, or "
                        "suppress with `# ray-lint: disable="
                        "dropped-object-ref` for intentional "
                        "fire-and-forget",
                    )
                )
        return out


# ----------------------------------------------------- resource-spec-validation

# Kept in sync with ray_tpu.core.api._VALID_OPTIONS via a unit test (the
# checker must not import the runtime: linting cannot depend on jax).
_VALID_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "num_returns",
    "max_retries", "max_restarts", "max_concurrency", "name",
    "scheduling_strategy", "memory", "runtime_env", "lifetime",
    "_backpressure_num_objects",
}

_PREDEFINED_RESOURCES = {"CPU", "GPU", "TPU", "memory", "object_store_memory"}

_NUMERIC_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "num_returns",
    "max_retries", "max_restarts", "max_concurrency",
}

# Calls whose `resources=` kwarg *registers* capacity (vs requesting it).
_REGISTRATION_CALLS = {"init", "add_node", "revive_node", "start_node"}


def _const_num(node) -> Optional[float]:
    """Numeric value of a literal, including the `-1` UnaryOp spelling."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    if (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
        and isinstance(node.operand.value, (int, float))
    ):
        return -node.operand.value
    return None


@register
class ResourceSpecChecker(Checker):
    name = "resource-spec-validation"
    description = (
        "task/actor resource spec the scheduler layer can never satisfy: "
        "unknown option, negative amount, predefined name in custom "
        "`resources`, or custom resource no node registers"
    )

    def __init__(self):
        # custom resource name -> first request site
        self._requested: Dict[str, Tuple[str, int, str]] = {}
        self._registered: Set[str] = set(_PREDEFINED_RESOURCES)

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
                for dec in _remote_decorator_calls(node):
                    self._check_options(
                        dec, ctx, out, strict_unknown=True
                    )
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "options":
                    known = any(
                        kw.arg in _VALID_OPTIONS for kw in node.keywords
                    )
                    if known:
                        self._check_options(
                            node, ctx, out, strict_unknown=False
                        )
                # capacity registration sites feed the known-names set
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if name in _REGISTRATION_CALLS:
                    for kw in node.keywords:
                        if kw.arg == "resources" and isinstance(
                            kw.value, ast.Dict
                        ):
                            for k in kw.value.keys:
                                if isinstance(k, ast.Constant) and isinstance(
                                    k.value, str
                                ):
                                    self._registered.add(k.value)
        return out

    def _check_options(self, call: ast.Call, ctx, out, strict_unknown: bool):
        for kw in call.keywords:
            if kw.arg is None:  # **kwargs — can't validate statically
                continue
            if strict_unknown and kw.arg not in _VALID_OPTIONS:
                out.append(
                    ctx.finding(
                        kw.value,
                        self.name,
                        f"unknown remote option `{kw.arg}` (valid: "
                        f"{', '.join(sorted(_VALID_OPTIONS))})",
                    )
                )
                continue
            v = kw.value
            if kw.arg in _NUMERIC_OPTIONS:
                num = _const_num(v)
                # -1 is the conventional "infinite" sentinel for retry
                # budgets (reference: ray.remote(max_retries=-1)).
                if (
                    kw.arg in ("max_retries", "max_restarts")
                    and num == -1
                ):
                    num = None
                if num is not None and num < 0:
                    out.append(
                        ctx.finding(
                            v,
                            self.name,
                            f"negative resource amount `{kw.arg}={num}` "
                            "can never be satisfied",
                        )
                    )
                if kw.arg == "max_concurrency" and num == 0:
                    out.append(
                        ctx.finding(
                            v, self.name, "`max_concurrency=0` — the actor "
                            "could never run a task",
                        )
                    )
            if kw.arg == "resources" and isinstance(v, ast.Dict):
                for k, val in zip(v.keys, v.values):
                    if not isinstance(k, ast.Constant):
                        continue
                    if not isinstance(k.value, str):
                        out.append(
                            ctx.finding(
                                k,
                                self.name,
                                f"resource name {k.value!r} must be a "
                                "string",
                            )
                        )
                        continue
                    if k.value in _PREDEFINED_RESOURCES:
                        out.append(
                            ctx.finding(
                                k,
                                self.name,
                                f"predefined resource `{k.value}` in "
                                "custom `resources=`; use the dedicated "
                                "option (num_cpus/num_gpus/num_tpus/"
                                "memory)",
                            )
                        )
                        continue
                    amount = _const_num(val)
                    if amount is not None and amount < 0:
                        out.append(
                            ctx.finding(
                                val,
                                self.name,
                                f"negative amount for resource "
                                f"`{k.value}`",
                            )
                        )
                    if k.value not in self._requested:
                        self._requested[k.value] = (
                            ctx.relpath,
                            k.lineno,
                            ctx.line_text(k.lineno),
                        )

    def finalize(self) -> List[Finding]:
        out: List[Finding] = []
        for name, (path, line, text) in sorted(self._requested.items()):
            if name not in self._registered:
                out.append(
                    Finding(
                        path=path,
                        line=line,
                        col=0,
                        check=self.name,
                        message=(
                            f"custom resource `{name}` is requested but no "
                            "scanned registration site (init/add_node) "
                            "ever provides it — tasks would pend forever"
                        ),
                        line_text=text,
                    )
                )
        return out


# --------------------------------------------------------- unbounded-rpc-call

# Directory segments that count as control plane: a blocked thread there
# wedges a daemon loop, the GCS, or a driver's submission path. serve/ is
# included since its fast path (serve/fastpath.py) talks to daemons
# directly for pair registration.
_CONTROL_PLANE_SEGMENTS = {"cluster", "dag", "serve"}


@register
class UnboundedRpcCallChecker(Checker):
    name = "unbounded-rpc-call"
    description = (
        "control-plane `.call(\"method\", ...)` without an explicit "
        "`timeout=`: the call rides the client-default deadline, which a "
        "daemon/GCS/driver loop never chose — every blocking rpc in "
        "cluster/ must bound its wait explicitly"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.replace("\\", "/").split("/")
        if not (set(parts[:-1]) & _CONTROL_PLANE_SEGMENTS):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "call"):
                continue
            # the rpc idiom: first positional arg is the method-name string
            # (skips unrelated `.call(x)` where x is a variable)
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            out.append(
                ctx.finding(
                    node,
                    self.name,
                    f"rpc `{node.args[0].value}` has no explicit timeout — "
                    "a hung peer wedges this thread for the client-default "
                    "window; pass `timeout=` (config rpc_call_timeout_s or "
                    "tighter), or suppress with `# ray-lint: "
                    "disable=unbounded-rpc-call`",
                )
            )
        return out


# ------------------------------------------------------ protocol checkers
#
# Four whole-program checks over the ProtocolIndex (analysis/protocol.py):
# the stringly-typed control plane gets the cross-referencing a generated
# gRPC stub would give the reference. Each checker builds the index during
# check_module and emits from finalize; every check self-gates on having
# seen the relevant counterpart surface (handlers, subscriptions, the
# config table) so linting a single file never false-positives.


class _ProtocolCheckerBase(Checker):
    def __init__(self):
        from ray_tpu.analysis.protocol import ProtocolIndex

        self.index = ProtocolIndex()

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        from ray_tpu.analysis.protocol import ProtocolIndex

        # the per-module AST extraction is cached on the ctx: all four
        # protocol checkers share one walk per file, merging cheap lists
        self.index.merge(ProtocolIndex.piece_for(ctx))
        return []

    @staticmethod
    def _site_finding(site, check: str, message: str) -> Finding:
        return Finding(
            path=site.path,
            line=site.line,
            col=0,
            check=check,
            message=message,
            line_text=site.line_text,
            end_line=site.end_line,
        )


@register
class RpcMethodUnknownChecker(_ProtocolCheckerBase):
    name = "rpc-method-unknown"
    description = (
        "`.call/.call_async/.notify(\"method\", ...)` whose string-literal "
        "method has NO `rpc_<method>` handler anywhere in the scanned tree "
        "— a typo'd or renamed rpc fails only at runtime with 'unknown "
        "method'"
    )

    def finalize(self) -> List[Finding]:
        known = self.index.handler_methods()
        if not known:
            return []  # no handler surface in scope: nothing to check against
        out: List[Finding] = []
        for site in self.index.calls:
            if site.method not in known:
                out.append(self._site_finding(
                    site, self.name,
                    f"rpc `{site.method}` has no rpc_{site.method} handler "
                    f"in the scanned tree (known methods: "
                    f"{len(known)}); typo, rename drift, or a handler "
                    "outside the scan",
                ))
        return out


@register
class RpcPayloadKeyMismatchChecker(_ProtocolCheckerBase):
    name = "rpc-payload-key-mismatch"
    description = (
        "literal payload-dict keys at a call site disagree with the "
        "`p[\"...\"]`/`p.get(\"...\")` keys the handler reads: a missing "
        "required key is a guaranteed KeyError in the handler; a key no "
        "handler ever reads is dead weight or rename drift"
    )

    def finalize(self) -> List[Finding]:
        out: List[Finding] = []
        for site in self.index.calls:
            if site.keys is None:
                continue  # payload is a variable/absent: uncheckable here
            candidates = self.index.handlers.get(site.method)
            if not candidates:
                continue  # rpc-method-unknown owns that case
            keys = set(site.keys)
            if not site.open_keys:
                # required keys: satisfied if ANY candidate handler's
                # required set is covered (methods like stream_item exist
                # on both gcs and daemon with different contracts)
                missing_per = [(h, h.required - keys) for h in candidates]
                if all(miss for _h, miss in missing_per):
                    h, miss = min(missing_per, key=lambda t: len(t[1]))
                    out.append(self._site_finding(
                        site, self.name,
                        f"rpc `{site.method}` payload is missing key(s) "
                        f"{sorted(miss)} that {h.path}:{h.line} reads as "
                        "required `p[\"...\"]`",
                    ))
            if all(not h.open_payload for h in candidates):
                readable = set()
                for h in candidates:
                    readable |= h.required | h.optional
                dead = sorted(keys - readable)
                if dead:
                    out.append(self._site_finding(
                        site, self.name,
                        f"rpc `{site.method}` payload key(s) {dead} are "
                        "never read by any handler — dead weight or a "
                        "renamed key the handler no longer sees",
                    ))
        return out


@register
class PushTopicUnknownChecker(_ProtocolCheckerBase):
    name = "push-topic-unknown"
    description = (
        "a push/broadcast topic literal that no `.subscribe(\"topic\")` in "
        "the scanned tree listens to: the frame is built, sent, and "
        "silently dropped at every client"
    )

    def finalize(self) -> List[Finding]:
        subscribed = self.index.subscribed_topics()
        if not subscribed:
            return []  # no subscriber surface in scope
        out: List[Finding] = []
        for site in self.index.pushes:
            if site.topic not in subscribed:
                out.append(self._site_finding(
                    site, self.name,
                    f"push topic `{site.topic}` has no subscriber in the "
                    "scanned tree — every delivery is silently dropped",
                ))
        return out


@register
class ConfigKeyUnknownChecker(_ProtocolCheckerBase):
    name = "config-key-unknown"
    description = (
        "a config-knob usage (attribute read on a Config/GLOBAL_CONFIG, an "
        "override-dict key, or a literal RAY_TPU_<lowercase> env name) "
        "that core/config.py's _DEFS table does not define: reads raise "
        "AttributeError at runtime, overrides raise ValueError, env knobs "
        "are silently ignored"
    )

    def finalize(self) -> List[Finding]:
        from ray_tpu.analysis.protocol import CONFIG_API_ATTRS

        defined = self.index.config_keys
        if not defined:
            return []  # _DEFS not in scope: nothing to validate against
        out: List[Finding] = []
        for use in self.index.config_uses:
            if use.key in defined or use.key in CONFIG_API_ATTRS:
                continue
            what = {
                "attr": "attribute read",
                "override": "override key",
                "env": "env knob",
            }[use.via]
            out.append(self._site_finding(
                use, self.name,
                f"config {what} `{use.key}` is not defined in "
                f"{self.index.config_defs_path} _DEFS — "
                + ("reads raise AttributeError" if use.via == "attr" else
                   "Config(overrides) raises ValueError" if use.via == "override"
                   else "the env var is silently ignored"),
            ))
        return out


# --------------------------------------------- lifecycle / thread checkers


@register
class IllegalStateTransitionChecker(Checker):
    name = "illegal-state-transition"
    description = (
        "a GCS/daemon handler writes an entity lifecycle state the "
        "declared state machine (analysis/statemachine.py) does not "
        "allow: an unknown state string (typo), a row created in a "
        "non-initial state, a state no declared edge produces, or a "
        "guarded write out of an observed state with no such edge"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        from ray_tpu.analysis import statemachine as sm

        if not sm.applies_to(ctx):
            return []
        out: List[Finding] = []
        for w, problem in sm.check_writes(sm.extract_module(ctx)):
            out.append(Finding(
                path=w.path, line=w.line, col=0, check=self.name,
                message=f"{problem} (in {w.func}); declare the edge in "
                        "statemachine.MACHINES if the protocol really "
                        "grew, or fix the write",
                line_text=w.line_text, end_line=w.end_line,
            ))
        return out


#: attribute-call names that mutate a container in place
_MUTATOR_ATTRS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "update", "setdefault", "extend", "insert",
    "move_to_end",
})

#: constructors whose result is a shared mutable container
_CONTAINER_CTORS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
})


@register
class CrossThreadFieldWriteChecker(Checker):
    """The static half of the hybrid race sanitizer. As a CHECKER it
    reports only high-confidence unlocked findings in the daemon/GCS;
    its extraction machinery (`_mutable_fields`/`_context_roots`/
    `_calls_of`/`_mutations` + lock propagation) is also reused by
    :func:`ray_tpu.analysis.racer.extract_watchlist` to emit the FULL
    claim surface over cluster//serve//dag/ — every >= 2-context field
    including the lock-protected ones with their credited lock attr —
    which the dynamic vector-clock stage then validates at runtime
    (``--dump-watchlist`` / ``--race``)."""

    name = "cross-thread-field-write"
    description = (
        "a GCS/daemon mutable container field is written from two "
        "different execution contexts (rpc-handler loop, push-subscriber "
        "thread, background thread, executor) with at least one write "
        "not under a class lock: read-modify-write races the GIL does "
        "not serialize"
    )

    #: execution-context roots by method-name shape
    _THREAD_SUFFIX = "_loop"

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        from ray_tpu.analysis import statemachine as sm

        if not sm.applies_to(ctx):
            return []
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(ctx, cls))
        return out

    # ------------------------------------------------------ class model

    def _check_class(self, ctx: ModuleContext,
                     cls: ast.ClassDef) -> List[Finding]:
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        lock_attrs = self._lock_attrs(cls)
        fields = self._mutable_fields(methods.get("__init__"))
        if not fields:
            return []
        roots = self._context_roots(cls, methods)
        if len({c for _m, c in roots}) < 2:
            return []  # single execution context: nothing can race
        # effective context/locked per method, propagated through the
        # same-class call graph (a helper called only under the lock
        # inherits lock-held-ness; the _locked suffix asserts it)
        reach: Dict[str, Set[Tuple[str, bool]]] = {}
        work = [(m, c, False) for m, c in roots if m in methods]
        while work:
            name, context, locked = work.pop()
            eff_locked = locked or name.endswith("_locked")
            key = (context, eff_locked)
            if key in reach.setdefault(name, set()):
                continue
            reach[name].add(key)
            for callee, call_locked in self._calls_of(
                methods[name], lock_attrs
            ):
                if callee in methods:
                    work.append((callee, context, eff_locked or call_locked))
        # collect mutations: field -> [(context, locked, node, method)]
        mutations: Dict[str, List[Tuple[str, bool, ast.AST, str]]] = {}
        for name, fn in methods.items():
            if name == "__init__":
                continue
            for context, locked in reach.get(name, ()):
                for field, node, in_with in self._mutations(fn, fields,
                                                            lock_attrs):
                    mutations.setdefault(field, []).append(
                        (context, locked or in_with, node, name)
                    )
        out: List[Finding] = []
        flagged: Set[int] = set()
        for field, muts in mutations.items():
            contexts = {c for c, _l, _n, _m in muts}
            if len(contexts) < 2:
                continue
            if all(locked for _c, locked, _n, _m in muts):
                continue
            for context, locked, node, mname in muts:
                if locked or id(node) in flagged:
                    continue
                flagged.add(id(node))
                others = sorted(contexts - {context}) or sorted(contexts)
                out.append(ctx.finding(
                    node, self.name,
                    f"`self.{field}` is mutated here on the {context} "
                    f"context without holding a class lock, and also "
                    f"from {', '.join(others)} — wrap both in `with "
                    f"self.{sorted(lock_attrs)[0] if lock_attrs else '_lock'}"
                    "`, or suppress with `# ray-lint: "
                    "disable=cross-thread-field-write` if the field is "
                    "provably confined",
                ))
        return out

    # ------------------------------------------------------- extraction

    @staticmethod
    def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                    and v.func.attr in ("Lock", "RLock", "Condition"):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name
                    ) and t.value.id == "self":
                        out.add(t.attr)
        return out

    @staticmethod
    def _mutable_fields(init) -> Set[str]:
        if init is None:
            return set()
        out: Set[str] = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                targets, v = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, v = [node.target], node.value
            else:
                continue
            is_container = isinstance(v, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(v, ast.Call) and (
                    (isinstance(v.func, ast.Name)
                     and v.func.id in _CONTAINER_CTORS)
                    or (isinstance(v.func, ast.Attribute)
                        and v.func.attr in _CONTAINER_CTORS)
                )
            )
            if not is_container:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ) and t.value.id == "self":
                    out.add(t.attr)
        return out

    def _context_roots(self, cls: ast.ClassDef,
                       methods) -> List[Tuple[str, str]]:
        """(method, context) execution entry points."""
        roots: List[Tuple[str, str]] = []
        for name in methods:
            if name.startswith("rpc_"):
                roots.append((name, "rpc-handler loop"))
            elif name.endswith(self._THREAD_SUFFIX):
                roots.append((name, "background thread"))
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else None
            # .subscribe("topic", self._on_x) -> client dispatch thread
            if attr == "subscribe" and len(node.args) > 1:
                m = self._self_method(node.args[1])
                if m:
                    roots.append((m, "push-subscriber thread"))
            # Thread(target=self._x)
            if attr == "Thread" or (isinstance(f, ast.Name)
                                    and f.id == "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        m = self._self_method(kw.value)
                        if m:
                            roots.append((m, "background thread"))
            # run_in_executor(None, self._x | lambda: self._x(...))
            if attr == "run_in_executor" and len(node.args) > 1:
                m = self._self_method(node.args[1])
                if m:
                    roots.append((m, "executor"))
            # on_disconnect=self._x runs on the server loop
            for kw in node.keywords:
                if kw.arg == "on_disconnect":
                    m = self._self_method(kw.value)
                    if m:
                        roots.append((m, "rpc-handler loop"))
        return roots

    @staticmethod
    def _self_method(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ) and expr.value.id == "self":
            return expr.attr
        if isinstance(expr, ast.Lambda):
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self":
                    return sub.func.attr
        return None

    def _calls_of(self, fn, lock_attrs) -> List[Tuple[str, bool]]:
        """Same-class ``self.m()`` calls with their lock-held-ness."""
        out: List[Tuple[str, bool]] = []
        locked_ids = self._nodes_under_lock(fn, lock_attrs)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                out.append((node.func.attr, id(node) in locked_ids))
        return out

    @staticmethod
    def _nodes_under_lock(fn, lock_attrs) -> Set[int]:
        """ids of AST nodes lexically inside `with self.<lock>:`."""
        out: Set[int] = set()

        def is_lock_with(w: ast.AST) -> bool:
            if not isinstance(w, ast.With):
                return False
            for item in w.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) and isinstance(
                    e.value, ast.Name
                ) and e.value.id == "self" and e.attr in lock_attrs:
                    return True
            return False

        def walk(node, locked):
            for child in ast.iter_child_nodes(node):
                child_locked = locked or is_lock_with(child)
                if child_locked:
                    out.add(id(child))
                    for sub in ast.walk(child):
                        out.add(id(sub))
                else:
                    walk(child, child_locked)

        walk(fn, False)
        return out

    def _mutations(self, fn, fields: Set[str],
                   lock_attrs: Set[str]) -> List[Tuple[str, ast.AST, bool]]:
        """(field, node, under_with_lock) mutation sites of tracked
        fields inside one method."""
        locked_ids = self._nodes_under_lock(fn, lock_attrs)
        out: List[Tuple[str, ast.AST, bool]] = []

        def self_field(expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ) and expr.value.id == "self" and expr.attr in fields:
                return expr.attr
            return None

        for node in ast.walk(fn):
            field = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    # self.F = ... (rebind) or self.F[k] = ...
                    field = self_field(t) or (
                        self_field(t.value)
                        if isinstance(t, ast.Subscript) else None
                    )
                    if field:
                        break
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        field = self_field(t.value)
                        if field:
                            break
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in _MUTATOR_ATTRS:
                field = self_field(node.func.value)
            if field:
                out.append((field, node, id(node) in locked_ids))
        return out


# ------------------------------------------------- metric hygiene checker

_METRIC_CLASSES = {
    "ray_tpu.util.metrics.Counter",
    "ray_tpu.util.metrics.Gauge",
    "ray_tpu.util.metrics.Histogram",
}
_METRIC_NAME_RE = re.compile(r"ray_tpu_[a-z0-9_]+\Z")


@register
class MetricNameChecker(Checker):
    """Two contracts on Counter/Gauge/Histogram constructions (the
    observability plane's lint half, ray_tpu.obs):

    - the metric name must match ``ray_tpu_[a-z0-9_]+`` — one namespace,
      Prometheus-safe, grep-able;
    - the construction must run at import time (module scope, class body,
      or ``__init__``): the registry is process-global and permanent, so a
      metric constructed per call/request leaks a registry entry per
      unique name and re-registers forever on the hot path.

    Non-literal names are skipped (dynamic factories judge themselves).
    """

    name = "metric-name-invalid"
    description = (
        "metric constructed with a non-`ray_tpu_[a-z0-9_]+` literal name, "
        "or outside module/__init__ scope (per-call registry leak)"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        imap = ImportMap(ctx.tree)
        out: List[Finding] = []

        def visit(node: ast.AST, func_stack: Tuple[str, ...]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack = func_stack + (node.name,)
            elif isinstance(node, ast.Call):
                resolved = imap.resolve(node.func)
                if resolved in _METRIC_CLASSES:
                    self._check_call(ctx, node, resolved, func_stack, out)
            for child in ast.iter_child_nodes(node):
                visit(child, func_stack)

        visit(ctx.tree, ())
        return out

    def _check_call(self, ctx: ModuleContext, node: ast.Call,
                    resolved: str, func_stack: Tuple[str, ...],
                    out: List[Finding]) -> None:
        cls = resolved.rsplit(".", 1)[1]
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None
        )
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            return  # dynamic name: out of scope
        if not _METRIC_NAME_RE.fullmatch(arg.value):
            out.append(ctx.finding(
                node, self.name,
                f"{cls} name {arg.value!r} does not match "
                "`ray_tpu_[a-z0-9_]+` — metrics share one cluster-wide "
                "Prometheus namespace; rename (or suppress with `# ray-"
                "lint: disable=metric-name-invalid`)",
            ))
        if func_stack and func_stack[-1] != "__init__":
            out.append(ctx.finding(
                node, self.name,
                f"{cls} {arg.value!r} constructed inside "
                f"`{func_stack[-1]}()`: the registry is process-global — "
                "construct metrics at module//__init__ scope and observe "
                "per call, or each call leaks a registry entry",
            ))


# ------------------------------------------------------- channel memory
#
# Access-discipline checkers for the dag seqlock channel (the static
# half of analysis/memmodel.py): the word-level model checker is only
# sound while ALL header/payload access funnels through the ChannelMem
# ops layer and the publication order the model verified is the order
# the code ships. Scoped to dag/ and object_store/ — the two subsystems
# built on (or absorbing) the channel's mmap machinery.

_CHAN_SCOPE_DIRS = ("dag", "object_store")
_MMAP_NAMES = ("mm", "_mm")


def _in_channel_scope(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return any(p in _CHAN_SCOPE_DIRS for p in parts[:-1])


@register
class ChanRawHeaderAccessChecker(Checker):
    """Any raw access to seqlock channel memory — ``struct``
    ``pack_into``/``unpack_from``, an ``mmap.mmap`` construction, or
    indexing an ``mm``/``_mm`` mapping — outside a ``*Mem`` ops-layer
    class. The memmodel checker verifies the protocol through the
    :class:`~ray_tpu.dag.channel.ChannelMem` seam; a header word poked
    anywhere else is invisible to it (and to the AST round-trip gate),
    so the model silently stops covering the shipped code."""

    name = "chan-raw-header-access"
    description = (
        "raw channel header/payload access (struct pack/unpack, "
        "mmap.mmap, mm[...] indexing) outside the ChannelMem ops layer "
        "in dag//object_store/"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        if not _in_channel_scope(ctx.relpath):
            return []
        imap = ImportMap(ctx.tree)
        out: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            out.append(ctx.finding(
                node, self.name,
                f"{what} outside a *Mem ops-layer class: every channel "
                "header-word/payload access must go through ChannelMem "
                "(dag/channel.py) so the memmodel checker keeps covering "
                "the real protocol",
            ))

        def visit(node: ast.AST, in_mem_class: bool) -> None:
            if isinstance(node, ast.ClassDef):
                in_mem_class = in_mem_class or node.name.endswith("Mem")
            elif not in_mem_class:
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in ("pack_into", "unpack_from"):
                        flag(node, f"struct .{node.func.attr}()")
                    elif imap.resolve(node.func) == "mmap.mmap":
                        flag(node, "mmap.mmap() construction")
                elif isinstance(node, ast.Subscript):
                    v = node.value
                    nm = v.attr if isinstance(v, ast.Attribute) else (
                        v.id if isinstance(v, ast.Name) else None
                    )
                    if nm in _MMAP_NAMES:
                        flag(node, f"`{nm}[...]` mapping access")
            for child in ast.iter_child_nodes(node):
                visit(child, in_mem_class)

        visit(ctx.tree, False)
        return out


@register
class ChanPublicationOrderChecker(Checker):
    """Seqlock publication order, statically enforced where the channel
    protocol is implemented (dag//object_store/): within one function,
    the payload store must precede the ``version`` bump (the commit a
    reader wakes on) and the payload copy must precede the ``ack``
    advance (which frees the writer to overwrite). The memmodel checker
    proved the inverted orders lose: a reader woken by an early
    ``version`` copies torn/stale bytes (seeded bug
    ``version-before-payload``); an early ``ack`` lets the writer
    overwrite mid-copy."""

    name = "chan-publication-order"
    description = (
        "channel `version`/`ack` published before the payload "
        "store/copy it guards (seqlock commit-order inversion)"
    )

    #: method attrs that move payload bytes (the ChannelMem seam ops and
    #: their raw struct-era spellings)
    _PAYLOAD_WRITES = ("write_payload",)
    _PAYLOAD_READS = ("read_payload",)
    #: method attrs that store a header word (first arg names the word)
    _WORD_STORES = ("_put", "store")

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        if not _in_channel_scope(ctx.relpath):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(ctx, node, out)
        return out

    def _check_function(self, ctx: ModuleContext, fn: ast.AST,
                        out: List[Finding]) -> None:
        payload_writes: List[int] = []
        payload_reads: List[int] = []
        word_stores: List[Tuple[str, ast.Call]] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._PAYLOAD_WRITES:
                payload_writes.append(node.lineno)
            elif attr in self._PAYLOAD_READS:
                payload_reads.append(node.lineno)
            elif attr in self._WORD_STORES and node.args:
                word = chan_word_of(node.args[0])
                if word in ("version", "ack"):
                    word_stores.append((word, node))
        for word, call in word_stores:
            if word == "version" and any(
                line > call.lineno for line in payload_writes
            ):
                out.append(ctx.finding(
                    call, self.name,
                    "`version` published before the payload store: a "
                    "reader woken by this bump copies torn/stale bytes "
                    "— commit order is payload, len, THEN version",
                ))
            elif word == "ack" and any(
                line > call.lineno for line in payload_reads
            ):
                out.append(ctx.finding(
                    call, self.name,
                    "`ack` advanced before the payload copy: the writer "
                    "is freed to overwrite the frame mid-copy — copy "
                    "the payload, THEN advance ack",
                ))


def static_lock_graph(paths, root=None):
    """The lock-order checker's accumulated graph for the given paths:
    ({node: {kind, where}}, {(src, dst): (path, line)}). Used by tests to
    cross-check the static graph against sanitizer-observed orderings.
    Raises on unparseable input — a silently empty graph would make that
    cross-check pass vacuously."""
    from ray_tpu.analysis.core import iter_modules

    chk = LockOrderCycleChecker()
    errors: List[str] = []
    for ctx in iter_modules(paths, root=root, errors=errors):
        chk.check_module(ctx)
    if errors:
        raise ValueError(
            "static_lock_graph: unparseable file(s): " + "; ".join(errors)
        )
    return chk.nodes, chk.edges


# --------------------------------------------------- rpc cost checkers
#
# The static halves of the RPC budget (analysis/rpcflow.py): the N+1
# pattern and the hold-a-lock-across-a-round-trip pattern. Both feed the
# sharding refactor (ROADMAP #1) — every fix is a deleted round trip or
# an unwedged control-plane thread.


@register
class RpcInLoopChecker(Checker):
    """Per-item RPC inside a loop where a batched counterpart exists —
    the N+1 chatter pattern the rpcflow cost table calls ``per-item``.
    Keyed on rpcflow.BATCHED_COUNTERPARTS so the checker never flags a
    loop that has no batched alternative to offer."""

    name = "rpc-in-loop"
    description = (
        "per-item `.call/.call_async(\"method\", ...)` inside a loop for "
        "a method with a batched counterpart: N frames (and for blocking "
        "calls, N round-trip latencies) where one would do"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        from ray_tpu.analysis.rpcflow import BATCHED_COUNTERPARTS

        parts = ctx.relpath.replace("\\", "/").split("/")
        if not (set(parts[:-1]) & _CONTROL_PLANE_SEGMENTS):
            return []
        out: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(ctx, fn, BATCHED_COUNTERPARTS, out)
        return out

    def _check_function(self, ctx, fn, counterparts, out) -> None:
        from ray_tpu.analysis.rpcflow import BATCH_PAYLOAD_KEYS

        parents = {
            id(child): parent for parent in ast.walk(fn)
            for child in ast.iter_child_nodes(parent)
        }
        seen: Set[int] = set()
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in ast.walk(loop):
                if node is loop or not isinstance(node, ast.Call) \
                        or id(node) in seen:
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr in ("call", "call_async")):
                    continue
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                method = node.args[0].value
                hint = counterparts.get(method)
                if hint is None:
                    continue
                # already the batched form: payload carries a batch key
                # (e.g. free_objects over an aggregated id list inside a
                # drain loop is one frame per BATCH, not per item)
                if len(node.args) > 1 and isinstance(node.args[1], ast.Dict) \
                        and any(
                            isinstance(k, ast.Constant)
                            and k.value in BATCH_PAYLOAD_KEYS
                            for k in node.args[1].keys
                        ):
                    continue
                # the loop exits right after the call (next sibling on the
                # climb to the loop is return/break/raise): at most one
                # frame per loop entry, e.g. publish-after-successful-pull
                if self._loop_exits_after(node, loop, parents):
                    continue
                seen.add(id(node))
                blocking = ("blocking round trip" if f.attr == "call"
                            else "frame")
                out.append(ctx.finding(
                    node, self.name,
                    f"per-item rpc `{method}` inside a loop: one "
                    f"{blocking} per item where a batched form exists — "
                    f"{hint}; or suppress with "
                    "`# ray-lint: disable=rpc-in-loop`",
                ))

    @staticmethod
    def _loop_exits_after(call: ast.AST, loop: ast.AST, parents) -> bool:
        """True when control provably leaves the loop right after the
        statement containing ``call``: climbing block-by-block toward the
        loop, the immediate next sibling is an unconditional
        return/break/raise before any other statement (or an inner loop
        boundary) intervenes."""
        node = call
        while node is not loop:
            parent = parents.get(id(node))
            if parent is None:
                return False
            if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)) \
                    and parent is not loop:
                return False  # inner loop body: still per-item there
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and node in block:
                    idx = block.index(node)
                    rest = block[idx + 1:]
                    if rest:
                        return isinstance(
                            rest[0], (ast.Return, ast.Break, ast.Raise)
                        )
                    break
            node = parent
        return False


@register
class RpcUnderLockChecker(Checker):
    """Blocking `.call` while a `threading` lock is held: the round trip
    (client-default deadline: seconds) serializes every other thread
    queued on that lock, and a lock-ordered peer calling back in deadlocks.
    Reuses CrossThreadFieldWriteChecker's lock machinery — `with
    self.<lock>:` scoping plus propagation through same-class calls made
    under the lock and the ``_locked`` suffix convention."""

    name = "rpc-under-lock"
    description = (
        "blocking `.call(\"method\", ...)` while holding a class "
        "`threading` lock: every thread queued on the lock eats the "
        "round-trip latency, and a callback from the peer deadlocks"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.replace("\\", "/").split("/")
        if not (set(parts[:-1]) & _CONTROL_PLANE_SEGMENTS):
            return []
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                self._check_class(ctx, cls, out)
        return out

    def _check_class(self, ctx, cls: ast.ClassDef, out) -> None:
        helper = CrossThreadFieldWriteChecker()
        lock_attrs = helper._lock_attrs(cls)
        if not lock_attrs:
            return
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # lock-held-ness propagated through the same-class call graph:
        # seed every method as a root (any caller context), then mark
        # callees reached from under a lock
        called_locked: Dict[str, bool] = {
            name: name.endswith("_locked") for name in methods
        }
        work = [n for n, locked in called_locked.items() if locked]
        for name, fn in methods.items():
            for callee, under in helper._calls_of(fn, lock_attrs):
                if under and callee in methods \
                        and not called_locked[callee]:
                    called_locked[callee] = True
                    work.append(callee)
        while work:
            name = work.pop()
            for callee, _under in helper._calls_of(
                methods[name], lock_attrs
            ):
                if callee in methods and not called_locked[callee]:
                    called_locked[callee] = True
                    work.append(callee)
        for name, fn in methods.items():
            locked_ids = helper._nodes_under_lock(fn, lock_attrs)
            whole_fn_locked = called_locked[name]
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "call"):
                    continue
                if not (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                if not (id(node) in locked_ids or whole_fn_locked):
                    continue
                how = (
                    "inside `with self.<lock>:`" if id(node) in locked_ids
                    else "in a method reached from under the class lock"
                )
                out.append(ctx.finding(
                    node, self.name,
                    f"blocking rpc `{node.args[0].value}` {how} "
                    f"({'/'.join(sorted(lock_attrs))}): hoist the call "
                    "out of the critical section (snapshot under the "
                    "lock, call after), or suppress with "
                    "`# ray-lint: disable=rpc-under-lock`",
                ))


@register
class BlockingWaitUnderLockChecker(Checker):
    """Generalizes `rpc-under-lock` to every OTHER blocking wait the
    waitgraph classifier knows (chained ``call_async(...).result()``,
    bare ``Future.result``, ``queue.get``, ``Condition.wait``,
    ``Thread.join``, ``Channel.read/write``): the lock is pinned for
    the whole wait, and whoever must release the awaited resource may
    need that lock — the lock-channel / lock-RPC halves of the wait
    cycles the dynamic WaitSanitizer hunts. Same lock machinery and
    same-class propagation as ``rpc-under-lock``; the ``with self._cv:
    self._cv.wait()`` condition idiom is exempt (waiting RELEASES the
    lock it waits on)."""

    name = "blocking-wait-under-lock"
    description = (
        "blocking wait (chained rpc result, future, queue get, "
        "condition wait, thread join, channel read/write) while "
        "holding a class `threading` lock: the lock is pinned for the "
        "whole wait and the releaser may need it"
    )

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        parts = ctx.relpath.replace("\\", "/").split("/")
        if not (set(parts[:-1]) & _CONTROL_PLANE_SEGMENTS):
            return []
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                self._check_class(ctx, cls, out)
        return out

    @staticmethod
    def _receiver_attr(node: ast.Call) -> Optional[str]:
        v = node.func.value
        if isinstance(v, ast.Attribute) and isinstance(
            v.value, ast.Name
        ) and v.value.id == "self":
            return v.attr
        return None

    def _check_class(self, ctx, cls: ast.ClassDef, out) -> None:
        from ray_tpu.analysis.racer import _locks_covering
        from ray_tpu.analysis.waitgraph import (
            WAIT_KINDS_UNDER_LOCK, blocking_wait_kind)

        helper = CrossThreadFieldWriteChecker()
        lock_attrs = helper._lock_attrs(cls)
        if not lock_attrs:
            return
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        called_locked: Dict[str, bool] = {
            name: name.endswith("_locked") for name in methods
        }
        work = [n for n, locked in called_locked.items() if locked]
        for name, fn in methods.items():
            for callee, under in helper._calls_of(fn, lock_attrs):
                if under and callee in methods \
                        and not called_locked[callee]:
                    called_locked[callee] = True
                    work.append(callee)
        while work:
            name = work.pop()
            for callee, _under in helper._calls_of(
                methods[name], lock_attrs
            ):
                if callee in methods and not called_locked[callee]:
                    called_locked[callee] = True
                    work.append(callee)
        for name, fn in methods.items():
            locked_ids = helper._nodes_under_lock(fn, lock_attrs)
            covering = _locks_covering(fn, lock_attrs)
            whole_fn_locked = called_locked[name]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                k = blocking_wait_kind(node)
                if k is None or k[0] not in WAIT_KINDS_UNDER_LOCK:
                    continue
                kind, method = k
                lexically = id(node) in locked_ids
                if not (lexically or whole_fn_locked):
                    continue
                if kind == "cond-wait":
                    # waiting on a condition RELEASES the lock it waits
                    # on: flag only when some OTHER lock stays held
                    recv = self._receiver_attr(node)
                    if lexically:
                        held = covering.get(id(node), frozenset())
                        if not (held - ({recv} if recv else set())):
                            continue
                    elif recv is not None and recv in lock_attrs:
                        continue
                how = (
                    "inside `with self.<lock>:`" if lexically
                    else "in a method reached from under the class lock"
                )
                what = f"blocking {kind}" + (
                    f" `{method}`" if method else ""
                )
                out.append(ctx.finding(
                    node, self.name,
                    f"{what} {how} ({'/'.join(sorted(lock_attrs))}): "
                    "the lock is pinned for the whole wait and the "
                    "releaser may need it — hoist the wait out of the "
                    "critical section (snapshot under the lock, wait "
                    "after), or suppress with "
                    "`# ray-lint: disable=blocking-wait-under-lock`",
                ))


@register
class RpcReentryCycleChecker(Checker):
    """A handler whose blocking RPC chain can re-enter its own server
    class — the GCS→daemon→GCS shape. With a bounded dispatcher every
    such chain is one concurrent burst away from thread exhaustion, and
    under a held lock it is a cross-process deadlock. Whole-program:
    modules accumulate through ``check_module`` (helpers outside the
    control plane must still resolve), the blocking graph builds once
    in ``finalize`` via :func:`ray_tpu.analysis.waitgraph.
    build_from_contexts` and every reentry chain is reported at the
    originating handler's first blocking RPC site."""

    name = "rpc-reentry-cycle"
    description = (
        "rpc handler whose blocking rpc chain re-enters its own server "
        "class: the reply depends on a dispatcher slot the caller may "
        "hold (thread exhaustion; deadlock under a lock)"
    )

    def __init__(self) -> None:
        self._ctxs: List[ModuleContext] = []

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        self._ctxs.append(ctx)
        return []

    def finalize(self) -> List[Finding]:
        from ray_tpu.analysis import waitgraph as _wg

        if not self._ctxs:
            return []
        report = _wg.build_from_contexts(self._ctxs, root="")
        out: List[Finding] = []
        for entry in _wg.reentry_chains(report):
            site = entry["site"]
            chain = " -> ".join(entry["chain"])
            out.append(Finding(
                path=site.path, line=site.line, col=0,
                check=self.name,
                message=(
                    f"blocking rpc `{site.method}` starts a chain that "
                    f"re-enters this handler's own server ({chain}): "
                    "the reply depends on a dispatcher slot the caller "
                    "may be holding — break the cycle (async notify, "
                    "or move the work off the handler), or suppress "
                    "with `# ray-lint: disable=rpc-reentry-cycle`"
                ),
                line_text="", end_line=site.end_line,
            ))
        return out
