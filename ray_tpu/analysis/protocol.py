"""Static RPC-protocol model extraction (AST, no runtime imports).

The control plane is stringly typed: ``client.call("submit_task", {...})``
is dispatched by name to ``GcsServer.rpc_submit_task`` and payload dicts
are read back as ``p["task_id"]`` / ``p.get("owner")``. Nothing ties the
two sides together at import time, so a typo'd method name, a renamed
payload key, or a push topic nobody subscribes to is invisible until a
live test happens to cross it. This module extracts the full protocol
surface from the AST — handlers (with the payload keys they read), call
sites (with the literal payload keys they send), push/subscribe topic
literals, and config-knob definitions/usages — into one inspectable
:class:`ProtocolIndex`. The protocol checkers in
:mod:`ray_tpu.analysis.checkers` consume it, and the CLI's
``--dump-protocol`` serializes it so the model is diffable and the
dynamic invariant checker's method table can be validated against it
(reference: the reference repo's generated gRPC stubs make this whole
class of drift a compile error; here the linter is the compiler).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set

from ray_tpu.analysis.core import ModuleContext

#: method-name prefix that marks a server-side handler
HANDLER_PREFIX = "rpc_"

#: attribute names that send a request with a string-literal method
CALL_ATTRS = ("call", "call_async", "notify")

#: (attribute name -> positional index of the topic argument) for
#: server->client pushes; wrappers in gcs.py and the RpcServer.send_push
#: seam take the topic second
PUSH_ATTRS = {"push": 0, "broadcast": 0, "_push_conn": 1,
              "_push_to_node": 1, "send_push": 1}

#: env literals like RAY_TPU_scheduling_policy are config knobs; the
#: all-caps infra vars (RAY_TPU_CHAOS_SPEC, RAY_TPU_WORKER_ID, ...) are not
_ENV_KNOB_RE = re.compile(r"^RAY_TPU_([a-z][a-z0-9_]*)$")

#: Config attributes that are API surface, not knobs (consumed by the
#: config-key-unknown checker — single definition, no drift)
CONFIG_API_ATTRS = frozenset({"to_dict", "_values"})


def _server_label(relpath: str) -> str:
    base = relpath.replace("\\", "/").rsplit("/", 1)[-1]
    if base == "gcs.py":
        return "gcs"
    if base == "node_daemon.py":
        return "daemon"
    return base[:-3] if base.endswith(".py") else base


@dataclasses.dataclass
class Handler:
    method: str
    server: str
    path: str
    line: int
    param: str
    required: Set[str] = dataclasses.field(default_factory=set)
    optional: Set[str] = dataclasses.field(default_factory=set)
    # True when the payload escapes whole (dict(p), **p, p.items(), passed
    # on): the key universe is then unknowable, so unknown-key checks are
    # suppressed (required-key reads still hold)
    open_payload: bool = False

    def to_dict(self) -> Dict:
        return {
            "method": self.method,
            "server": self.server,
            "path": self.path,
            "line": self.line,
            "required": sorted(self.required),
            "optional": sorted(self.optional),
            "open_payload": self.open_payload,
        }


@dataclasses.dataclass
class CallSite:
    path: str
    line: int
    line_text: str
    end_line: int
    kind: str  # call | call_async | notify
    method: str
    # literal payload-dict keys, or None when the payload is a variable /
    # absent; open_keys marks a dict literal with non-literal parts (**x)
    keys: Optional[List[str]] = None
    open_keys: bool = False

    def to_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "kind": self.kind,
            "method": self.method,
            "keys": self.keys,
            "open_keys": self.open_keys,
        }


@dataclasses.dataclass
class TopicSite:
    path: str
    line: int
    line_text: str
    end_line: int
    topic: str
    via: str  # push | broadcast | _push_conn | _push_to_node | subscribe

    def to_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "topic": self.topic,
            "via": self.via,
        }


@dataclasses.dataclass
class ConfigUse:
    path: str
    line: int
    line_text: str
    end_line: int
    key: str
    via: str  # attr | override | env

    def to_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "key": self.key,
            "via": self.via,
        }


class ProtocolIndex:
    """Whole-program protocol surface, built one module at a time."""

    def __init__(self):
        self.handlers: Dict[str, List[Handler]] = {}
        self.calls: List[CallSite] = []
        self.pushes: List[TopicSite] = []
        self.subscriptions: List[TopicSite] = []
        self.config_keys: Set[str] = set()
        self.config_defs_path: Optional[str] = None
        self.config_uses: List[ConfigUse] = []
        # per-entity lifecycle writes (analysis/statemachine.py): the
        # extracted counterpart of the declared MACHINES table
        self.state_writes: List = []

    # ------------------------------------------------------------ building

    def add_module(self, ctx: ModuleContext) -> None:
        from ray_tpu.analysis import statemachine as _sm

        self._collect_handlers(ctx)
        self._collect_wire_sites(ctx)
        self._collect_config_defs(ctx)
        self._collect_config_uses(ctx)
        self.state_writes.extend(_sm.extract_module(ctx))

    @classmethod
    def piece_for(cls, ctx: ModuleContext) -> "ProtocolIndex":
        """The single-module extraction, computed once per ModuleContext
        and cached on it: four protocol checkers run per lint pass, and
        the AST walks are the expensive part — they must not quadruple."""
        piece = getattr(ctx, "_protocol_index_piece", None)
        if piece is None:
            piece = cls()
            piece.add_module(ctx)
            ctx._protocol_index_piece = piece
        return piece

    def merge(self, other: "ProtocolIndex") -> None:
        """Fold another index (typically a per-module piece) into this one."""
        for m, hs in other.handlers.items():
            self.handlers.setdefault(m, []).extend(hs)
        self.calls.extend(other.calls)
        self.pushes.extend(other.pushes)
        self.subscriptions.extend(other.subscriptions)
        self.config_keys |= other.config_keys
        if other.config_defs_path is not None:
            self.config_defs_path = other.config_defs_path
        self.config_uses.extend(other.config_uses)
        self.state_writes.extend(other.state_writes)

    def _collect_handlers(self, ctx: ModuleContext) -> None:
        server = _server_label(ctx.relpath)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith(HANDLER_PREFIX):
                continue
            args = [a.arg for a in node.args.args if a.arg != "self"]
            if not args:
                continue
            h = Handler(
                method=node.name[len(HANDLER_PREFIX):],
                server=server,
                path=ctx.relpath,
                line=node.lineno,
                param=args[0],
            )
            self._scan_payload_reads(node, h)
            self.handlers.setdefault(h.method, []).append(h)

    @staticmethod
    def _scan_payload_reads(fn: ast.AST, h: Handler) -> None:
        """Classify every use of the payload param inside the handler:
        ``p["k"]`` loads are required keys, ``p.get("k")`` optional; any
        other use of the bare name means the payload escapes (open)."""
        consumed: Set[int] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == h.param
            ):
                consumed.add(id(node.value))
                key = node.slice
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    if isinstance(node.ctx, ast.Load):
                        h.required.add(key.value)
                    # Store/Del = handler-added keys, not caller contract
                else:
                    h.open_payload = True  # p[var]: unknowable key
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == h.param
            ):
                consumed.add(id(node.func.value))
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    h.optional.add(node.args[0].value)
                else:
                    h.open_payload = True
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and node.id == h.param
                and id(node) not in consumed
                and isinstance(node.ctx, ast.Load)
            ):
                h.open_payload = True
                return

    def _collect_wire_sites(self, ctx: ModuleContext) -> None:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in CALL_ATTRS:
                if not (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                site = CallSite(
                    path=ctx.relpath,
                    line=node.lineno,
                    line_text=ctx.line_text(node.lineno),
                    end_line=getattr(node, "end_lineno", None) or node.lineno,
                    kind=attr,
                    method=node.args[0].value,
                )
                if len(node.args) > 1 and isinstance(node.args[1], ast.Dict):
                    keys: List[str] = []
                    open_keys = False
                    for k in node.args[1].keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            keys.append(k.value)
                        else:  # **expansion or computed key
                            open_keys = True
                    site.keys = keys
                    site.open_keys = open_keys
                self.calls.append(site)
            elif attr in PUSH_ATTRS:
                idx = PUSH_ATTRS[attr]
                if len(node.args) > idx and isinstance(
                    node.args[idx], ast.Constant
                ) and isinstance(node.args[idx].value, str):
                    self.pushes.append(TopicSite(
                        path=ctx.relpath,
                        line=node.lineno,
                        line_text=ctx.line_text(node.lineno),
                        end_line=getattr(node, "end_lineno", None) or node.lineno,
                        topic=node.args[idx].value,
                        via=attr,
                    ))
            elif attr == "subscribe":
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    self.subscriptions.append(TopicSite(
                        path=ctx.relpath,
                        line=node.lineno,
                        line_text=ctx.line_text(node.lineno),
                        end_line=getattr(node, "end_lineno", None) or node.lineno,
                        topic=node.args[0].value,
                        via="subscribe",
                    ))

    def _collect_config_defs(self, ctx: ModuleContext) -> None:
        """Knob names from the ``_DEFS`` table in core/config.py (or any
        module declaring one at top level)."""
        for node in ctx.tree.body if isinstance(ctx.tree, ast.Module) else ():
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "_DEFS" not in targets:
                continue
            value = node.value
            # handle the annotated/dict-literal form only
            if isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        self.config_keys.add(k.value)
                self.config_defs_path = ctx.relpath
        for node in ctx.tree.body if isinstance(ctx.tree, ast.Module) else ():
            if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ) and node.target.id == "_DEFS" and isinstance(
                node.value, ast.Dict
            ):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        self.config_keys.add(k.value)
                self.config_defs_path = ctx.relpath

    # --- config usage extraction ---

    @classmethod
    def _is_configish(cls, expr: ast.AST) -> bool:
        """Does this RHS expression EVALUATE TO a ray_tpu Config? True for
        GLOBAL_CONFIG references, ``Config(...)`` calls, and boolean/
        conditional compositions of those — `cfg = config or Config()`,
        `cfg = config if ... else _config.GLOBAL_CONFIG`. Deliberately
        structural, not containment: `Cluster(config=Config(...))` builds
        a Cluster, not a Config, and must not mark the target."""
        if isinstance(expr, ast.BoolOp):
            return any(cls._is_configish(v) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return cls._is_configish(expr.body) or cls._is_configish(expr.orelse)
        if isinstance(expr, ast.Name):
            return expr.id == "GLOBAL_CONFIG"
        if isinstance(expr, ast.Attribute):
            return expr.attr == "GLOBAL_CONFIG"
        if isinstance(expr, ast.Call):
            f = expr.func
            return (isinstance(f, ast.Name) and f.id == "Config") or (
                isinstance(f, ast.Attribute) and f.attr == "Config"
            )
        return False

    def _config_use(self, ctx: ModuleContext, node: ast.AST, key: str,
                    via: str) -> None:
        self.config_uses.append(ConfigUse(
            path=ctx.relpath,
            line=node.lineno,
            line_text=ctx.line_text(node.lineno),
            end_line=getattr(node, "end_lineno", None) or node.lineno,
            key=key,
            via=via,
        ))

    def _collect_config_uses(self, ctx: ModuleContext) -> None:
        if ctx.relpath == self.config_defs_path or ctx.relpath.replace(
            "\\", "/"
        ).endswith("core/config.py"):
            return  # the defining module's internals aren't knob uses
        # (1) env literals
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                m = _ENV_KNOB_RE.match(node.value)
                if m:
                    self._config_use(ctx, node, m.group(1), "env")
        # (2) override-dict literals: Config({...}) / Config(overrides={...})
        #     / set_global_config({...}) / _system_config={...}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            dicts: List[ast.Dict] = []
            if name in ("Config", "set_global_config"):
                if node.args and isinstance(node.args[0], ast.Dict):
                    dicts.append(node.args[0])
                for kw in node.keywords:
                    if kw.arg == "overrides" and isinstance(kw.value, ast.Dict):
                        dicts.append(kw.value)
            for kw in node.keywords:
                if kw.arg == "_system_config" and isinstance(kw.value, ast.Dict):
                    dicts.append(kw.value)
            for d in dicts:
                for k in d.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        self._config_use(ctx, k, k.value, "override")
        # (3) attribute reads on config-typed expressions
        self._collect_config_attr_reads(ctx)

    def _collect_config_attr_reads(self, ctx: ModuleContext) -> None:
        # class-level: self.<attr> assigned from a config-ish RHS anywhere
        # in the class -> reads of self.<attr>.<knob> in that class count
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            config_attrs: Set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and self._is_configish(node.value):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            config_attrs.add(t.attr)
            if not config_attrs:
                continue
            for node in ast.walk(cls):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Attribute)
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "self"
                    and node.value.attr in config_attrs
                ):
                    self._config_use(ctx, node, node.attr, "attr")
        # function-local names assigned from config-ish RHS, plus direct
        # GLOBAL_CONFIG.<knob> reads anywhere
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            config_names: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and self._is_configish(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            config_names.add(t.id)
            if not config_names:
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in config_names
                ):
                    self._config_use(ctx, node, node.attr, "attr")
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and (
                    (isinstance(node.value, ast.Name)
                     and node.value.id == "GLOBAL_CONFIG")
                    or (isinstance(node.value, ast.Attribute)
                        and node.value.attr == "GLOBAL_CONFIG")
                )
            ):
                self._config_use(ctx, node, node.attr, "attr")

    # ----------------------------------------------------------- queries

    def handler_methods(self) -> Set[str]:
        return set(self.handlers)

    def subscribed_topics(self) -> Set[str]:
        return {s.topic for s in self.subscriptions}

    # --------------------------------------------------------------- dump

    def to_dict(self) -> Dict:
        return {
            "handlers": {
                m: [h.to_dict() for h in hs]
                for m, hs in sorted(self.handlers.items())
            },
            "calls": [c.to_dict() for c in self.calls],
            "pushes": [p.to_dict() for p in self.pushes],
            "subscriptions": [s.to_dict() for s in self.subscriptions],
            "config": {
                "defined": sorted(self.config_keys),
                "defs_path": self.config_defs_path,
                "uses": [u.to_dict() for u in self.config_uses],
            },
            "statemachines": {
                "declared": {
                    name: m.to_dict()
                    for name, m in sorted(_machines().items())
                },
                "writes": [w.to_dict() for w in self.state_writes],
            },
        }


def _machines():
    from ray_tpu.analysis.statemachine import MACHINES

    return MACHINES


def extract_protocol(paths, root=None) -> ProtocolIndex:
    """Build the protocol index for the .py files under ``paths``.
    Raises on unparseable input — a silently partial model would make
    every cross-check pass vacuously (same contract as
    ``static_lock_graph``)."""
    from ray_tpu.analysis.core import iter_modules

    idx = ProtocolIndex()
    errors: List[str] = []
    for mctx in iter_modules(paths, root=root, errors=errors):
        idx.add_module(mctx)
    if errors:
        raise ValueError(
            "extract_protocol: unparseable file(s): " + "; ".join(errors)
        )
    return idx
