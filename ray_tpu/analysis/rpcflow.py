"""Interprocedural RPC-cost analysis + per-operation RPC budget ratchet.

BENCH_r05 showed the scheduling kernel doing 9.6M decisions/s while the
cluster tops out at ~2.9k tasks/s end-to-end: per-task control-plane RPC
chatter is the bottleneck, and the planned daemon-local-lease refactor
(ROADMAP #1, the Raylet/GCS split) is *about* deleting round trips. This
module answers, statically and machine-readably, "how many control-plane
RPCs does each driver-facing operation cost, and where do they come
from?" — and freezes the answer in a committed budget so CI refuses any
PR that sneaks a new per-task round trip in.

Three pieces, in the house style (static claim -> dynamic verification ->
honesty gate):

- **Static** (`build_rpcflow`): an interprocedural call graph from the
  public entry points (client.py driver API, dag execute, serve handle
  request, autoscaler tick, daemon/GCS background loops) down to every
  `.call` / `.call_async` / `.notify` / push site, reusing protocol.py's
  RPC-surface tables (CALL_ATTRS/PUSH_ATTRS + literal-method extraction).
  Each reachable site is classified by multiplicity: ``per-call`` (runs
  once per operation), ``per-item`` (inside a loop, with loop-nest
  depth — the N+1 smell), ``amortized`` (behind a `not in` cache-miss
  guard), ``once`` (behind an `is None`/`not flag` one-shot guard), or
  ``batched`` (payload carries a list-valued batch key). `--dump-rpcflow`
  prints the per-operation cost table.

- **Dynamic** (`RpcProfiler`): a transparent wrapper over the `rpc.TRACE`
  seam that attributes round trips / notifies / pushes / frame bytes to
  driver *operation spans* (thread-local stack, entered via the
  `util.tracing.PROFILE` seam by client.py / dag/compiled.py /
  serve/handle.py). Everything the inner tracer (flight recorder or
  invariant tracer) does is delegated, so the profiler stacks on top of
  either without changing semantics.

- **Gate** (`check_measured` / `ratchet_check`, driven by
  ``lint_gate --rpc-budget``): measured per-operation RPC counts must fit
  the committed `.rpc-budget.json` AND the statically-predicted
  multiplicity class (a zero-RPC op must measure zero). Budget entries
  may decrease, never increase — the ratchet the sharding refactor will
  prove its >= 10x against.

Reference: Ray's own GCS-chatter postmortems (task submission cost in
rounds trips is the headline metric of the Raylet split), plus the
rpc-metrics tables gcs_server emits per method.
"""

from __future__ import annotations

import ast
import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.analysis.core import ModuleContext, iter_modules
from ray_tpu.analysis.protocol import CALL_ATTRS, PUSH_ATTRS

# --------------------------------------------------------------- constants

#: payload-dict keys whose presence marks a call site as carrying a batch
#: (one frame, N items) rather than a per-item round trip
BATCH_PAYLOAD_KEYS = frozenset({
    "object_ids", "results", "tasks", "items", "updates", "events",
    "specs", "batch", "bundles", "metrics",
})

#: per-item methods with a known batched counterpart in this tree — the
#: table the `rpc-in-loop` checker keys on. Values are the remediation
#: hint shown in the finding.
BATCHED_COUNTERPARTS: Dict[str, str] = {
    "add_object_location": (
        "send one call with `object_ids=[...]` (the handler accepts the "
        "batched form; task_done already reports result locations in one "
        "frame)"
    ),
    "free_objects": (
        "already takes `object_ids` — aggregate the ids and send one call"
    ),
    "note_object": (
        "aggregate into the next heartbeat or send one batched "
        "`add_object_location` with `object_ids=[...]`"
    ),
}

#: entry points the cost table is computed from:
#: op name -> (relpath suffix, class name or None, function name)
ENTRY_POINTS: Dict[str, Tuple[str, Optional[str], str]] = {
    "submit_task": ("cluster/client.py", "ClusterClient", "submit_task"),
    "get": ("cluster/client.py", "ClusterClient", "get"),
    "wait": ("cluster/client.py", "ClusterClient", "wait"),
    "put": ("cluster/client.py", "ClusterClient", "put"),
    # the actor-call frame is sent by the per-actor dispatcher thread
    # (ordered submission), not by the enqueue in _submit_actor_call_meta
    "actor_call": ("cluster/client.py", "ClusterClient",
                   "_actor_dispatch_loop"),
    # actor creation rides submit_task with spec.actor_creation=True (the
    # register_actor branch); same entry, budgeted separately
    "actor_create": ("cluster/client.py", "ClusterClient", "submit_task"),
    "pg_create": ("cluster/client.py", "ClusterClient",
                  "create_placement_group"),
    "dag_execute": ("dag/compiled.py", "CompiledDAG", "execute"),
    "serve_request": ("serve/fastpath.py", "FastPathRouter", "submit"),
    "autoscaler_tick": ("autoscaler/autoscaler.py", "Autoscaler", "_loop"),
    "daemon_heartbeat": ("cluster/node_daemon.py", "NodeDaemon",
                         "_heartbeat_loop"),
    "gcs_sched_loop": ("cluster/gcs.py", "GcsServer", "_sched_loop"),
}

#: loops are the *body* of these entry ops; one "operation" is one pass,
#: so the top-level While of the loop function itself does not count as
#: per-item nesting
_LOOP_BODY_OPS = frozenset({
    "autoscaler_tick", "daemon_heartbeat", "gcs_sched_loop", "actor_call",
})

_MAX_DEPTH = 4          # loop-nest depth cap (memoization granularity)
_MAX_CHAIN = 24         # call-chain length cap
_MULT_ORDER = {"repair": 0, "once": 1, "amortized": 2, "batched": 3,
               "per-call": 4, "per-item": 5}

# ------------------------------------------------------------ static model


@dataclasses.dataclass
class SiteUse:
    """One RPC site as reached from one entry operation."""

    path: str
    line: int
    kind: str           # call | call_async | notify | push
    method: str         # literal method/topic, or "<dynamic>"
    target: str         # receiver expression text, e.g. "self.gcs"
    depth: int          # accumulated loop-nest depth along the chain
    guard: Optional[str]  # "once" | "amortized" | None
    mclass: str         # once|amortized|batched|per-call|per-item
    via: Tuple[str, ...]  # qualname chain from the entry function

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path, "line": self.line, "kind": self.kind,
            "method": self.method, "target": self.target,
            "depth": self.depth, "guard": self.guard, "class": self.mclass,
            "via": list(self.via),
        }


@dataclasses.dataclass
class OpCost:
    """Per-operation cost row: every RPC site reachable from the entry."""

    op: str
    entry: str                 # "cluster/client.py:ClusterClient.submit_task"
    sites: List[SiteUse] = dataclasses.field(default_factory=list)

    @property
    def steady_sites(self) -> List[SiteUse]:
        """Sites that cost a frame on EVERY operation (per-call/per-item/
        batched round trips and notifies; once/amortized excluded)."""
        return [s for s in self.sites
                if s.mclass in ("per-call", "per-item", "batched")
                and s.kind in ("call", "call_async", "notify")]

    @property
    def predicted_class(self) -> str:
        """zero | bounded | per-item — the claim the dynamic gate checks."""
        steady = self.steady_sites
        if not steady:
            return "zero"
        if any(s.mclass == "per-item" for s in steady):
            return "per-item"
        return "bounded"

    @property
    def bounded_count(self) -> int:
        """Upper bound of steady-state frames/op for a `bounded` op."""
        return len(self.steady_sites)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "op": self.op, "entry": self.entry,
            "predicted_class": self.predicted_class,
            "bounded_count": self.bounded_count,
            "sites": [s.to_dict() for s in self.sites],
        }


@dataclasses.dataclass
class RpcFlowReport:
    ops: Dict[str, OpCost]
    functions_indexed: int
    files_scanned: int
    unresolved_entries: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "functions_indexed": self.functions_indexed,
            "files_scanned": self.files_scanned,
            "unresolved_entries": self.unresolved_entries,
            "ops": {k: v.to_dict() for k, v in sorted(self.ops.items())},
        }


@dataclasses.dataclass
class _FuncInfo:
    key: Tuple[str, str]       # (relpath, qualname)
    relpath: str
    cls: Optional[str]
    name: str
    node: Any                  # ast.FunctionDef | ast.AsyncFunctionDef


class _FuncIndex:
    """Whole-tree function table with the pragmatic resolvers the call
    graph uses: ``self.m()`` -> same class, bare ``f()`` -> same module
    then unique global, ``obj.m()`` -> unique method name repo-wide."""

    def __init__(self) -> None:
        self.funcs: Dict[Tuple[str, str], _FuncInfo] = {}
        self._module_fns: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._class_methods: Dict[Tuple[str, str, str], Tuple[str, str]] = {}
        self._by_method: Dict[str, List[Tuple[str, str]]] = {}
        self._by_name: Dict[str, List[Tuple[str, str]]] = {}
        self.files = 0

    def add_module(self, ctx: ModuleContext) -> None:
        self.files += 1
        rel = ctx.relpath.replace("\\", "/")
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add(rel, None, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._add(rel, node.name, sub)

    def _add(self, rel: str, cls: Optional[str], node) -> None:
        qual = f"{cls}.{node.name}" if cls else node.name
        key = (rel, qual)
        info = _FuncInfo(key=key, relpath=rel, cls=cls, name=node.name,
                         node=node)
        self.funcs[key] = info
        if cls is None:
            self._module_fns[(rel, node.name)] = key
            self._by_name.setdefault(node.name, []).append(key)
        else:
            self._class_methods[(rel, cls, node.name)] = key
            self._by_method.setdefault(node.name, []).append(key)

    def lookup(self, rel: str, cls: Optional[str],
               name: str) -> Optional[_FuncInfo]:
        key = (self._class_methods.get((rel, cls, name))
               if cls else self._module_fns.get((rel, name)))
        return self.funcs.get(key) if key else None

    def resolve_call(self, call: ast.Call, caller: _FuncInfo
                     ) -> Optional[_FuncInfo]:
        f = call.func
        if isinstance(f, ast.Name):
            # bare f(): same module first, else unique repo-wide
            info = self.lookup(caller.relpath, None, f.id)
            if info is not None:
                return info
            cands = self._by_name.get(f.id, [])
            return self.funcs[cands[0]] if len(cands) == 1 else None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and caller.cls is not None:
                # the class is known: a miss means a stored callable or
                # an inherited method — falling back to the unique-name
                # heuristic here fabricates cross-class edges
                return self.lookup(caller.relpath, caller.cls, f.attr)
            if f.attr.startswith("__"):
                return None
            # obj.m(): only when the method name is unambiguous repo-wide
            cands = self._by_method.get(f.attr, [])
            if len(cands) == 1:
                return self.funcs[cands[0]]
        return None


def _guard_kind(test: ast.AST) -> Optional[str]:
    """Classify an if-test as a cache/one-shot miss guard.

    ``x not in cache`` -> "amortized" (container membership: pays a frame
    only on cache misses); ``x is None`` / ``not x`` -> "once" (scalar
    one-shot flag: pays a frame on first use). An ``and``-conjunction is
    a miss guard if any conjunct is (the branch runs at most when that
    conjunct holds)."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            g = _guard_kind(v)
            if g is not None:
                return g
        return None
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        op = test.ops[0]
        if isinstance(op, ast.NotIn):
            return "amortized"
        if isinstance(op, ast.Is) and isinstance(
            test.comparators[0], ast.Constant
        ) and test.comparators[0].value is None:
            return "once"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, (ast.Name, ast.Attribute)):
        return "once"
    return None


def _hit_guard(test: ast.AST, ret: ast.Return) -> bool:
    """True for a cache-HIT early exit: `if p is not None: return p` /
    `if k in cache: return cache[k]`. The returned value must share a
    name with the test — a dispatch branch that early-returns something
    unrelated (`if spec.actor_id is not None: ...; return refs`) is a
    code path split, not a cache hit, and the fall-through is still
    steady state."""

    def _matches(t: ast.AST) -> bool:
        if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And):
            return any(_matches(v) for v in t.values)
        if isinstance(t, ast.Compare) and len(t.ops) == 1:
            op = t.ops[0]
            if isinstance(op, ast.In):
                return True
            if isinstance(op, ast.IsNot) and isinstance(
                t.comparators[0], ast.Constant
            ) and t.comparators[0].value is None:
                return True
        return False

    if not _matches(test) or ret.value is None:
        return False
    test_names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
    test_names |= {n.attr for n in ast.walk(test)
                   if isinstance(n, ast.Attribute)}
    ret_names = {n.id for n in ast.walk(ret.value)
                 if isinstance(n, ast.Name)}
    ret_names |= {n.attr for n in ast.walk(ret.value)
                  if isinstance(n, ast.Attribute)}
    return bool(test_names & ret_names)


def _expr_text(node: ast.AST, limit: int = 40) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # noqa: BLE001 - unparse is best-effort labeling
        s = "<expr>"
    return s if len(s) <= limit else s[: limit - 3] + "..."


def _literal_method(call: ast.Call, argpos: int = 0) -> str:
    if len(call.args) > argpos and isinstance(
        call.args[argpos], ast.Constant
    ) and isinstance(call.args[argpos].value, str):
        return call.args[argpos].value
    return "<dynamic>"


def _payload_keys(call: ast.Call) -> Optional[List[str]]:
    """Literal keys of a dict-literal payload (2nd positional arg)."""
    if len(call.args) < 2 or not isinstance(call.args[1], ast.Dict):
        return None
    keys = []
    for k in call.args[1].keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.append(k.value)
    return keys


def _classify(kind: str, method: str, keys: Optional[List[str]],
              depth: int, guard: Optional[str]) -> str:
    if guard == "repair":
        return "repair"
    if keys and BATCH_PAYLOAD_KEYS & set(keys):
        return "batched"
    if guard is not None:
        return guard
    if depth > 0:
        return "per-item"
    return "per-call"


class _Walker:
    """DFS from one entry function, tracking loop depth + cache guards."""

    def __init__(self, index: _FuncIndex) -> None:
        self.index = index
        self.sites: List[SiteUse] = []
        # (funckey, capped depth, guard) -> visited: bounds re-walks while
        # still letting the same helper contribute at different depths
        self._seen: Set[Tuple[Tuple[str, str], int, Optional[str]]] = set()

    def walk(self, info: _FuncInfo, depth: int = 0,
             guard: Optional[str] = None,
             chain: Tuple[str, ...] = ()) -> None:
        key = (info.key, min(depth, _MAX_DEPTH), guard)
        if key in self._seen or len(chain) >= _MAX_CHAIN:
            return
        self._seen.add(key)
        chain = chain + (f"{info.relpath}:{info.key[1]}",)
        self._visit_body(info.node.body, info, depth, guard, chain)

    # ------------------------------------------------------ body traversal

    def _visit_body(self, stmts, info, depth, guard, chain) -> None:
        for st in stmts:
            self._visit_stmt(st, info, depth, guard, chain)
            # early-return cache hit (`if p is not None: return p`): the
            # rest of this block is the miss path
            if guard is None and isinstance(st, ast.If) and st.body \
                    and isinstance(st.body[-1], ast.Return) \
                    and not st.orelse \
                    and _hit_guard(st.test, st.body[-1]):
                guard = "amortized"

    def _visit_stmt(self, st, info, depth, guard, chain) -> None:
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._visit_expr(st.iter, info, depth, guard, chain)
            self._visit_body(st.body, info, depth + 1, guard, chain)
            self._visit_body(st.orelse, info, depth, guard, chain)
            return
        if isinstance(st, ast.While):
            self._visit_expr(st.test, info, depth, guard, chain)
            self._visit_body(st.body, info, depth + 1, guard, chain)
            self._visit_body(st.orelse, info, depth, guard, chain)
            return
        if isinstance(st, ast.If):
            self._visit_expr(st.test, info, depth, guard, chain)
            g = _guard_kind(st.test)
            self._visit_body(st.body, info, depth, g or guard, chain)
            self._visit_body(st.orelse, info, depth, guard, chain)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (callback): body runs at most once per outer call
            # in every pattern this tree uses — walk it at current depth
            self._visit_body(st.body, info, depth, guard, chain)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, (ast.Try,)):
            self._visit_body(st.body, info, depth, guard, chain)
            for h in st.handlers:
                # except bodies are fault-repair paths, not steady state
                self._visit_body(h.body, info, depth, guard or "repair",
                                 chain)
            self._visit_body(st.orelse, info, depth, guard, chain)
            self._visit_body(st.finalbody, info, depth, guard, chain)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._visit_expr(item.context_expr, info, depth, guard,
                                 chain)
            self._visit_body(st.body, info, depth, guard, chain)
            return
        # leaf statements: scan embedded expressions for calls
        for sub in ast.iter_child_nodes(st):
            self._visit_expr(sub, info, depth, guard, chain)

    def _visit_expr(self, expr, info, depth, guard, chain) -> None:
        if expr is None or isinstance(expr, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef)):
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehension element + conditions run per item: one extra
            # loop level for everything inside
            for sub in ast.iter_child_nodes(expr):
                self._visit_expr(sub, info, depth + 1, guard, chain)
            return
        if isinstance(expr, ast.Call):
            self._handle_call(expr, info, depth, guard, chain)
        for sub in ast.iter_child_nodes(expr):
            self._visit_expr(sub, info, depth, guard, chain)

    # ----------------------------------------------------------- call sites

    def _handle_call(self, call: ast.Call, info, depth, guard,
                     chain) -> None:
        f = call.func
        eff_depth = depth
        if isinstance(f, ast.Attribute):
            if f.attr in CALL_ATTRS and call.args:
                # zero-arg .notify()/.call() is threading.Condition or an
                # unrelated callable — the rpc idiom always passes the
                # method name first
                method = _literal_method(call)
                self.sites.append(SiteUse(
                    path=info.relpath, line=call.lineno, kind=f.attr,
                    method=method, target=_expr_text(f.value),
                    depth=eff_depth, guard=guard,
                    mclass=_classify(f.attr, method, _payload_keys(call),
                                     eff_depth, guard),
                    via=chain,
                ))
                return
            if f.attr in PUSH_ATTRS:
                pos = PUSH_ATTRS[f.attr]
                method = _literal_method(call, pos)
                # pushes with a dict payload right after the topic
                keys = None
                if len(call.args) > pos + 1 and isinstance(
                    call.args[pos + 1], ast.Dict
                ):
                    keys = [k.value for k in call.args[pos + 1].keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)]
                self.sites.append(SiteUse(
                    path=info.relpath, line=call.lineno, kind="push",
                    method=method, target=_expr_text(f.value),
                    depth=eff_depth, guard=guard,
                    mclass=_classify("push", method, keys, eff_depth,
                                     guard),
                    via=chain,
                ))
                return
        callee = self.index.resolve_call(call, info)
        if callee is not None:
            self.walk(callee, eff_depth, guard, chain)


def build_rpcflow(paths: Sequence[str], root: str) -> RpcFlowReport:
    """Index the tree, then trace each entry operation to its RPC sites."""
    index = _FuncIndex()
    for ctx in iter_modules(paths, root):
        index.add_module(ctx)
    ops: Dict[str, OpCost] = {}
    unresolved: List[str] = []
    for op, (suffix, cls, name) in sorted(ENTRY_POINTS.items()):
        info = None
        for (rel, _qual), fi in index.funcs.items():
            if rel.endswith(suffix) and fi.cls == cls and fi.name == name:
                info = fi
                break
        if info is None:
            unresolved.append(op)
            continue
        w = _Walker(index)
        w.walk(info)
        sites = w.sites
        if op in _LOOP_BODY_OPS:
            # one operation == one pass of the loop body: strip the loop
            # function's own top-level While from every site's depth
            sites = [dataclasses.replace(
                s, depth=max(0, s.depth - 1),
                mclass=_classify(s.kind, s.method, None,
                                 max(0, s.depth - 1), s.guard)
                if s.mclass in ("per-call", "per-item") else s.mclass,
            ) for s in sites]
        entry = f"{info.relpath}:{info.key[1]}"
        ops[op] = OpCost(op=op, entry=entry, sites=sites)
    return RpcFlowReport(ops=ops, functions_indexed=len(index.funcs),
                         files_scanned=index.files,
                         unresolved_entries=unresolved)


def format_rpcflow(report: RpcFlowReport) -> str:
    lines = [
        f"rpcflow: {report.functions_indexed} functions over "
        f"{report.files_scanned} files",
    ]
    if report.unresolved_entries:
        lines.append(
            f"  UNRESOLVED entries: {', '.join(report.unresolved_entries)}"
        )
    for op, cost in sorted(report.ops.items()):
        steady = cost.steady_sites
        lines.append(
            f"\n{op}  [{cost.predicted_class}"
            + (f", <= {cost.bounded_count} frames/op"
               if cost.predicted_class == "bounded" else "")
            + f"]  entry={cost.entry}"
        )
        for s in sorted(cost.sites,
                        key=lambda s: (-_MULT_ORDER[s.mclass], s.path,
                                       s.line)):
            d = f" depth={s.depth}" if s.mclass == "per-item" else ""
            lines.append(
                f"  {s.mclass:>9}{d}  {s.kind:>10} {s.method:<24} "
                f"{s.target:<22} {s.path}:{s.line}"
            )
        if not cost.sites:
            lines.append("  (no reachable RPC sites)")
    return "\n".join(lines)


# --------------------------------------------------------- dynamic profiler


class _OpStats:
    __slots__ = ("invocations", "calls", "notifies", "pushes", "bytes")

    def __init__(self) -> None:
        self.invocations = 0
        self.calls = 0
        self.notifies = 0
        self.pushes = 0
        self.bytes = 0

    def to_dict(self) -> Dict[str, int]:
        return {"invocations": self.invocations, "calls": self.calls,
                "notifies": self.notifies, "pushes": self.pushes,
                "bytes": self.bytes}


class RpcProfiler:
    """Per-operation RPC profiler riding the ``rpc.TRACE`` seam.

    Installs as a TRANSPARENT wrapper: every tracer hook is counted and
    then delegated to whatever tracer was installed before (the default
    flight recorder, the invariant tracer, or nothing), so stacking the
    profiler never changes recording/invariant semantics. Operation spans
    are entered by the driver entry points via the ``tracing.PROFILE``
    seam (zero overhead when no profiler is installed: a module-global
    ``is None`` check, same discipline as ``rpc.TRACE`` itself)."""

    is_rpc_profiler = True

    def __init__(self) -> None:
        self._ops: Dict[str, _OpStats] = {}
        self._unattributed = _OpStats()
        # frames by RPC method, across ALL threads — background-plane
        # frames (daemon/GCS loops) carry no driver op span, so a regrown
        # N+1 there surfaces here, not in the per-op table
        self._methods: Dict[str, int] = {}
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._inner: Any = None
        self._installed = False

    # ------------------------------------------------------------ lifecycle

    def install(self) -> "RpcProfiler":
        from ray_tpu.cluster import rpc as rpc_mod
        from ray_tpu.util import tracing

        if self._installed:
            return self
        self._inner = rpc_mod.TRACE
        rpc_mod.TRACE = self
        tracing.PROFILE = self
        self._installed = True
        return self

    def uninstall(self) -> None:
        from ray_tpu.cluster import rpc as rpc_mod
        from ray_tpu.util import tracing

        if not self._installed:
            return
        if rpc_mod.TRACE is self:
            rpc_mod.TRACE = self._inner
        if tracing.PROFILE is self:
            tracing.PROFILE = None
        self._installed = False

    # ----------------------------------------------------------- op spans

    def _stack(self) -> List[List[Any]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def op_begin(self, name: str) -> List[Any]:
        # frame: [name, t0, stats-delta] — mutated in place by the hooks
        frame = [name, time.time(), _OpStats()]
        self._stack().append(frame)
        return frame

    def op_end(self, frame: List[Any]) -> None:
        from ray_tpu.util import tracing

        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is frame:
                del st[i]
                break
        name, t0, delta = frame
        with self._mu:
            agg = self._ops.get(name)
            if agg is None:
                agg = self._ops[name] = _OpStats()
            agg.invocations += 1
            agg.calls += delta.calls
            agg.notifies += delta.notifies
            agg.pushes += delta.pushes
            agg.bytes += delta.bytes
        tracing.record_span(
            f"op:{name}", t0, time.time(), rpcs=delta.calls,
            notifies=delta.notifies, pushes=delta.pushes,
            rpc_bytes=delta.bytes,
        )

    @contextlib.contextmanager
    def operation(self, name: str):
        frame = self.op_begin(name)
        try:
            yield
        finally:
            self.op_end(frame)

    def _current(self) -> Optional[_OpStats]:
        st = getattr(self._tls, "stack", None)
        return st[-1][2] if st else None

    # ----------------------------------------------- counted tracer hooks

    def on_send(self, src: str, dst: str, method: str):
        # counting happens in on_send_bytes (which also knows frame size
        # and call-vs-notify); this hook only preserves inner semantics
        inner = self._inner
        return inner.on_send(src, dst, method) if inner is not None else None

    def on_send_bytes(self, method: str, nbytes: int, kind: str) -> None:
        cur = self._current()
        if cur is None:
            with self._mu:
                self._bump(self._unattributed, kind, nbytes)
                self._methods[method] = self._methods.get(method, 0) + 1
            return
        self._bump(cur, kind, nbytes)
        with self._mu:
            self._methods[method] = self._methods.get(method, 0) + 1

    @staticmethod
    def _bump(stats: _OpStats, kind: str, nbytes: int) -> None:
        if kind == "notify":
            stats.notifies += 1
        else:
            stats.calls += 1
        stats.bytes += nbytes

    def on_push(self, server: str, peer: str, channel: str):
        cur = self._current()
        if cur is None:
            with self._mu:
                self._unattributed.pushes += 1
        else:
            cur.pushes += 1
        inner = self._inner
        if inner is not None:
            return inner.on_push(server, peer, channel)
        return None

    # -------------------------------------------- pure-delegation hooks

    def on_recv(self, *a, **kw):
        inner = self._inner
        return inner.on_recv(*a, **kw) if inner is not None else None

    def apply(self, kind, **fields):
        inner = self._inner
        return inner.apply(kind, **fields) if inner is not None else None

    def merge_clock(self, clock):
        inner = self._inner
        return inner.merge_clock(clock) if inner is not None else None

    def __getattr__(self, name: str):
        # transparent facade: unknown attrs (is_flight_recorder, ring
        # dumps, ...) resolve against the wrapped tracer
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # ------------------------------------------------------------ results

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "ops": {k: v.to_dict() for k, v in sorted(self._ops.items())},
                "unattributed": self._unattributed.to_dict(),
                "methods": dict(sorted(self._methods.items())),
            }

    def method_count(self, method: str) -> int:
        with self._mu:
            return self._methods.get(method, 0)

    def reset(self) -> None:
        """Zero the aggregates (keeps op spans live). Callers measuring
        steady state run a warmup pass, reset(), then the measured pass —
        once/amortized sites pay their frames before the reset."""
        with self._mu:
            self._ops.clear()
            self._unattributed = _OpStats()
            self._methods.clear()

    def per_op_rpcs(self) -> Dict[str, float]:
        """Round trips + notifies per invocation, by operation."""
        with self._mu:
            return {
                name: (s.calls + s.notifies) / max(1, s.invocations)
                for name, s in self._ops.items()
            }


@contextlib.contextmanager
def profiled_operation(name: str):
    """Module-level convenience for call sites that don't hold a profiler
    reference: no-op when no profiler is installed."""
    from ray_tpu.util import tracing

    p = tracing.PROFILE
    if p is None:
        yield
        return
    frame = p.op_begin(name)
    try:
        yield
    finally:
        p.op_end(frame)


# ---------------------------------------------------------- budget ratchet

DEFAULT_BUDGET_FILE = ".rpc-budget.json"

#: ops whose committed budget MUST be zero steady-state frames — the
#: flight-recorder-proven claims of PR 4 (dag) and PR 9 (serve fast path)
ZERO_STEADY_STATE_OPS = ("dag_execute", "serve_request")


def load_budget(path: str) -> Dict[str, Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    ops = data.get("ops")
    if not isinstance(ops, dict):
        raise ValueError(f"{path}: missing 'ops' table")
    return ops


def ratchet_check(committed: Dict[str, Dict[str, Any]],
                  proposed: Dict[str, Dict[str, Any]]) -> List[str]:
    """Budget entries may decrease, never increase; ops may be added but
    never dropped. Returns violation strings (empty == ok)."""
    errors: List[str] = []
    for op, entry in sorted(committed.items()):
        new = proposed.get(op)
        if new is None:
            errors.append(f"{op}: budgeted operation dropped from the table")
            continue
        old_v, new_v = float(entry["rpcs"]), float(new["rpcs"])
        if new_v > old_v:
            errors.append(
                f"{op}: budget raised {old_v:g} -> {new_v:g} — the ratchet "
                "only goes down; fix the regression instead"
            )
    for op in ZERO_STEADY_STATE_OPS:
        entry = proposed.get(op) or committed.get(op)
        if entry is not None and float(entry["rpcs"]) != 0:
            errors.append(f"{op}: must stay at 0 steady-state RPCs")
    return errors


def check_measured(measured: Dict[str, float],
                   budget: Dict[str, Dict[str, Any]],
                   report: Optional[RpcFlowReport] = None) -> List[str]:
    """The honesty gate: measured per-op frames must fit the committed
    budget AND the statically-predicted multiplicity class."""
    errors: List[str] = []
    for op, entry in sorted(budget.items()):
        if op not in measured:
            errors.append(f"{op}: budgeted but not measured")
            continue
        got, allowed = measured[op], float(entry["rpcs"])
        if got > allowed + 1e-9:
            errors.append(
                f"{op}: measured {got:.2f} RPCs/op over budget "
                f"{allowed:g} — a new round trip snuck in"
            )
        if report is not None and op in report.ops:
            pred = report.ops[op].predicted_class
            if pred == "zero" and got > 1e-9:
                errors.append(
                    f"{op}: statically predicted zero steady-state RPCs "
                    f"but measured {got:.2f}/op"
                )
            elif pred == "bounded" and got > report.ops[op].bounded_count:
                errors.append(
                    f"{op}: measured {got:.2f}/op exceeds the static "
                    f"bound of {report.ops[op].bounded_count} reachable "
                    "per-call sites"
                )
    return errors


def budget_table(measured: Dict[str, float],
                 report: Optional[RpcFlowReport] = None) -> str:
    lines = [f"{'operation':<18} {'RPCs/op':>8}  {'static class':<10}"]
    for op in sorted(measured):
        pred = (report.ops[op].predicted_class
                if report is not None and op in report.ops else "-")
        lines.append(f"{op:<18} {measured[op]:>8.2f}  {pred:<10}")
    return "\n".join(lines)


def repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )


# ------------------------------------------------------ measurement driver


def measure_rpc_budget(iters: int = 12, warmup: int = 3) -> Dict[str, Any]:
    """Spin an embedded one-node cluster and drive every budgeted driver
    operation under the :class:`RpcProfiler`.

    Steady-state discipline: a warmup pass pays every once/amortized frame
    (function/actor exports, serve pair registration, dag compile), then
    the profiler is reset and the measured pass runs. Returns
    ``{"iters", "per_op", "snapshot"}`` where ``per_op`` is round
    trips + notifies per invocation by operation — the numbers the
    committed ``.rpc-budget.json`` freezes.

    Shared by ``lint_gate --rpc-budget`` (in-process gate) and
    ``bench.py rpc_budget``.
    """
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(1)
    ray_tpu.init(address=cluster.address,
                 config={"serve_fastpath_refresh_s": 60.0,
                         "log_to_driver": False})
    prof = RpcProfiler().install()
    compiled = None
    try:
        @ray_tpu.remote
        def _noop(x):
            return x

        @ray_tpu.remote
        def _inc(x):
            return x + 1

        @ray_tpu.remote
        class _Counter:
            def __init__(self):
                self.n = 0

            def bump(self, k=1):
                self.n += k
                return self.n

        @serve.deployment(fast_path=True)
        def _echo(payload):
            return payload

        handle = serve.run(_echo.bind(), route_prefix=None)
        with InputNode() as inp:
            dag = _inc.bind(inp)
        compiled = dag.compile()
        actor = _Counter.remote()

        def drive(n: int) -> None:
            refs = [_noop.remote(i) for i in range(n)]        # submit_task
            for r in refs:
                ray_tpu.get(r)                                # get
            for r in refs:
                ray_tpu.wait([r], num_returns=1, timeout=10)  # wait
            for i in range(n):
                ray_tpu.put({"i": i})                         # put
            arefs = [actor.bump.remote() for _ in range(n)]   # actor_call
            for r in arefs:
                ray_tpu.get(r)
            for _ in range(max(1, n // 4)):                   # actor_create
                a = _Counter.remote()
                ray_tpu.get(a.bump.remote())
                ray_tpu.kill(a)
            for _ in range(max(1, n // 4)):                   # pg_create
                pg = placement_group([{"CPU": 1}], strategy="PACK")
                remove_placement_group(pg)
            for i in range(n):                                # dag_execute
                compiled.execute(i)
            for i in range(n):                                # serve_request
                handle.remote({"x": i}).result(timeout=30)

        drive(warmup)
        prof.reset()
        drive(iters)
        per_op = prof.per_op_rpcs()
        snap = prof.snapshot()
        return {
            "iters": iters,
            "per_op": {k: round(v, 4) for k, v in sorted(per_op.items())},
            "snapshot": snap,
        }
    finally:
        prof.uninstall()
        if compiled is not None:
            try:
                compiled.teardown()
            except Exception:  # noqa: BLE001
                pass
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()
        cluster.shutdown()
