"""Analysis framework core: findings, pragmas, checker registry, baseline.

The distributed-correctness linter walks Python ASTs with small visitor
classes (one per check) registered in a plugin table, mirroring how the
reference hardens its C++ core-worker/raylet layer with clang-tidy plugins
and TSAN annotations — here the failure surface is hand-rolled Python
concurrency (per-actor asyncio loops, threaded RPC/GCS loops, lock-guarded
stores), so the checks target *distributed* correctness: blocking calls on
event loops, unserializable closure captures, lock-order cycles, dropped
ObjectRefs, and resource specs the scheduler can never satisfy.

Suppression: per-line ``# ray-lint: disable=<check>[,<check>...]`` pragmas
(``disable=all`` wildcard), or ``# ray-lint: skip-file`` anywhere in a file.
A committed JSON baseline grandfathers known findings by content
fingerprint (path + check + stripped source line + occurrence ordinal),
so moved code keeps its baseline entry but *new* violations — including a
second copy of an already-baselined line — always fail.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Finding:
    path: str  # relative to the analysis root
    line: int
    col: int
    check: str
    message: str
    line_text: str = ""  # stripped source line, for fingerprinting
    # Ordinal among findings with identical (path, check, line_text),
    # assigned by analyze_paths. Without it, a *new* violation textually
    # identical to a baselined one in the same file would silently ride
    # the grandfathered entry, defeating the ratchet.
    occurrence: int = 0
    # Last physical line of the flagged node (= line for single-line
    # nodes); pragma lookup covers the whole range. Not fingerprinted.
    end_line: int = 0

    def fingerprint(self) -> str:
        # Content-addressed (no line number): moving code keeps the
        # baseline entry; editing the flagged line — or adding another
        # identical violation — makes a finding new.
        h = hashlib.sha1(
            f"{self.path}::{self.check}::{self.line_text}"
            f"::{self.occurrence}".encode()
        )
        return h.hexdigest()[:16]

    def to_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "check": self.check,
            "message": self.message,
            "fingerprint": self.fingerprint(),
        }

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"


# --------------------------------------------------------------------- pragmas

_PRAGMA_RE = re.compile(
    r"#\s*ray-lint:\s*(disable|skip-file)\b(?:\s*=\s*([\w\-,\s]+))?"
)


class Pragmas:
    """Per-line suppression table parsed from source comments.

    Only real COMMENT tokens count: a docstring that *documents* the
    pragma syntax (as this module's does) must not suppress anything."""

    def __init__(self, source: str):
        self.skip_file = False
        self.by_line: Dict[int, set] = {}
        try:
            comments = [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []  # unparseable files surface as errors elsewhere
        for lineno, text in comments:
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            kind, arg = m.group(1), m.group(2)
            if kind == "skip-file":
                self.skip_file = True
            elif arg:
                checks = {c.strip() for c in arg.split(",") if c.strip()}
                self.by_line.setdefault(lineno, set()).update(checks)

    def suppressed(self, finding: Finding) -> bool:
        if self.skip_file:
            return True
        # A multi-line statement can carry its pragma on any of its
        # physical lines (typically the closing one), so honor the
        # finding's whole lineno..end_lineno range.
        for lineno in range(finding.line, max(finding.line, finding.end_line) + 1):
            checks = self.by_line.get(lineno)
            if checks and ("all" in checks or finding.check in checks):
                return True
        return False


# -------------------------------------------------------------------- checkers


class ModuleContext:
    """Everything a checker needs about one parsed file."""

    def __init__(self, path: str, relpath: str, source: str, tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.modname = os.path.splitext(os.path.basename(path))[0]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node: ast.AST, check: str, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            path=self.relpath,
            line=line,
            col=col,
            check=check,
            message=message,
            line_text=self.line_text(line),
            end_line=getattr(node, "end_lineno", None) or line,
        )


class Checker:
    """Base checker. One instance lives for the whole run: per-module state
    goes through ``check_module``; whole-program checks (the lock graph)
    accumulate there and emit from ``finalize``."""

    name: str = ""
    description: str = ""

    def check_module(self, ctx: ModuleContext) -> List[Finding]:
        return []

    def finalize(self) -> List[Finding]:
        return []


CHECKERS: Dict[str, type] = {}


def register(cls):
    """Plugin-table registration decorator for checker classes."""
    assert cls.name, "checker must define a name"
    CHECKERS[cls.name] = cls
    return cls


def chan_word_of(node: ast.AST) -> Optional[str]:
    """Layout name of a channel-header word constant (``_W_VERSION`` /
    ``W_CAP`` -> "version" / "capacity"), else None. The ONE recognizer
    shared by the chan-publication-order checker and memmodel's
    op-sequence extraction — two copies would let the lint and the
    round-trip gate diverge on what counts as a word reference."""
    if isinstance(node, ast.Name) and node.id.startswith(("_W_", "W_")):
        name = node.id.split("W_", 1)[1].lower()
        return {"cap": "capacity"}.get(name, name)
    return None


# ----------------------------------------------------------------------- graphs


def find_cycles(adj: Dict) -> List[List]:
    """Elementary cycles in a directed graph given as ``{node: [succ, ...]}``,
    deduplicated by node set. Shared by the static lock-order checker and the
    runtime sanitizer so the two halves can never diverge on what counts as a
    cycle. Self-loops are the caller's concern (both graphs exclude them at
    edge insertion)."""
    out: List[List] = []
    seen: set = set()

    def dfs(start, node, path, visiting):
        for nxt in adj.get(node, ()):
            if nxt == start:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    out.append(list(path))
            elif nxt not in visiting and nxt in adj:
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return out


# ---------------------------------------------------------------------- runner


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    # Deduped by absolute path: overlapping arguments (`ray_tpu
    # ray_tpu/serve`) must not scan a file twice — duplicate findings
    # would shift occurrence ordinals and break baseline fingerprints.
    seen: set = set()

    def emit(p: str) -> Iterable[str]:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            yield p

    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield from emit(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", ".ray_tpu")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield from emit(os.path.join(dirpath, fn))


def iter_modules(
    paths: Sequence[str],
    root: Optional[str] = None,
    errors: Optional[List[str]] = None,
) -> Iterable[ModuleContext]:
    """Yield a ModuleContext per parseable .py file under ``paths``
    (deduped); unreadable/unparseable files are appended to ``errors``.
    The single read/parse/relpath loop shared by ``analyze_paths`` and
    ``checkers.static_lock_graph``."""
    root = os.path.abspath(root or os.getcwd())
    for path in iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            if errors is not None:
                errors.append(f"{path}: {e}")
            continue
        relpath = os.path.relpath(os.path.abspath(path), root)
        yield ModuleContext(path, relpath, source, tree)


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    suppressed: int
    errors: List[str]
    files_scanned: int


def analyze_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    select: Optional[Sequence[str]] = None,
) -> AnalysisResult:
    """Run every registered checker (or the ``select`` subset) over the
    .py files under ``paths``. Pragma-suppressed findings are dropped."""
    # Import for side effect: populates CHECKERS.
    from ray_tpu.analysis import checkers as _checkers  # noqa: F401

    names = list(select) if select else sorted(CHECKERS)
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        raise ValueError(f"unknown checks: {unknown}; have {sorted(CHECKERS)}")
    instances = [CHECKERS[n]() for n in names]

    findings: List[Finding] = []
    errors: List[str] = []
    suppressed = 0
    files_scanned = 0
    # relpath -> Pragmas, so finalize() findings get pragma treatment too
    pragma_tables: Dict[str, Pragmas] = {}

    for ctx in iter_modules(paths, root=root, errors=errors):
        files_scanned += 1
        pragmas = Pragmas(ctx.source)
        pragma_tables[ctx.relpath] = pragmas
        for chk in instances:
            for f_ in chk.check_module(ctx):
                if pragmas.suppressed(f_):
                    suppressed += 1
                else:
                    findings.append(f_)

    for chk in instances:
        for f_ in chk.finalize():
            table = pragma_tables.get(f_.path)
            if table is not None and table.suppressed(f_):
                suppressed += 1
            else:
                findings.append(f_)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    counts: Dict[Tuple[str, str, str], int] = {}
    for f_ in findings:
        key = (f_.path, f_.check, f_.line_text)
        f_.occurrence = counts.get(key, 0)
        counts[key] = f_.occurrence + 1
    return AnalysisResult(
        findings=findings,
        suppressed=suppressed,
        errors=errors,
        files_scanned=files_scanned,
    )


# -------------------------------------------------------------------- baseline


def load_baseline(path: str) -> Dict[str, Dict]:
    """Baseline file: {"findings": {fingerprint: example entry}}. Missing
    file means empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("findings", {}))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = {f.fingerprint(): f.to_dict() for f in findings}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "comment": (
                    "ray_tpu.analysis ratchet baseline: grandfathered "
                    "findings by content fingerprint. Entries may only be "
                    "removed (fixed), never added by hand — regenerate with "
                    "python -m ray_tpu.analysis <paths> --update-baseline."
                ),
                "findings": entries,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")


def split_by_baseline(
    findings: Sequence[Finding], baseline: Dict[str, Dict]
) -> Tuple[List[Finding], List[Finding]]:
    """Partition findings into (new, grandfathered)."""
    new: List[Finding] = []
    known: List[Finding] = []
    for f in findings:
        (known if f.fingerprint() in baseline else new).append(f)
    return new, known
