"""Runtime lock-order sanitizer: a ThreadSanitizer-style happens-before
lock-order recorder for the Python layer.

``LockOrderSanitizer.install()`` monkeypatches ``threading.Lock`` /
``threading.RLock`` factories so every lock allocated afterwards is wrapped
in an instrumented shim. Each acquisition records, per OS thread, the
currently-held lock set and adds ``held -> acquiring`` edges to a global
order graph keyed by the lock's *allocation site* (file:line), the runtime
analogue of the static checker's ``Class.attr`` nodes. ``cycles()`` then
reports any cyclic ordering actually observed — the dynamic cross-check
for the static ``lock-order-cycle`` checker (tests opt in via the
``lock_sanitizer`` conftest fixture).

The shim forwards everything else (``locked``, ``_is_owned``, …) to the
real lock, so ``threading.Condition`` built on an instrumented lock keeps
working: Condition binds ``acquire``/``release`` from the shim, and its
default wait/notify path calls straight through them.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.core import find_cycles

_THIS_FILE = __file__

# Module-level recording state. uninstall() cannot unwrap locks that were
# already handed out, so a shim may outlive its creating sanitizer; edges
# must therefore route through whichever sanitizer is *currently* active
# (else an inversion between an old-wrapped and a new-wrapped lock lands
# in neither graph), and the per-thread held stack must be shared so
# cross-install nestings are seen at all.
_active: Optional["LockOrderSanitizer"] = None
_held_tls = threading.local()


def _held_stack() -> List[Tuple[str, int]]:
    st = getattr(_held_tls, "stack", None)
    if st is None:
        st = _held_tls.stack = []
    return st


def _caller_site(depth: int = 2) -> Tuple[str, int]:
    """Allocation site of the lock: first frame outside this module and
    outside threading.py (Condition() allocates an RLock internally)."""
    f = sys._getframe(depth)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and not fn.endswith("threading.py"):
            return (fn, f.f_lineno)
        f = f.f_back
    return ("<unknown>", 0)


class _InstrumentedLock:
    """Wraps a real Lock/RLock; records acquisition order per thread
    (through the module's currently-active sanitizer, not necessarily
    the one that wrapped it)."""

    def __init__(self, inner, site: Tuple[str, int]):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held = _held_stack()
            san = _active
            if san is not None:
                san._record(held, self._site)
            held.append(self._site)
        return ok

    def release(self):
        held = _held_stack()
        # Locks are usually released LIFO; tolerate out-of-order release.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._site:
                del held[i]
                break
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # RLock's _release_save/_acquire_restore/_is_owned (used by
        # Condition) and anything else fall through to the real lock.
        return getattr(self._inner, name)


class LockOrderSanitizer:
    def __init__(self):
        self._graph_mu = threading.Lock()  # guards edges/sites; never wrapped
        # (src_site, dst_site) -> observation count
        self.edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]], int] = {}
        self.sites: Set[Tuple[str, int]] = set()
        self._installed = False
        self._orig_lock = None
        self._orig_rlock = None

    # ------------------------------------------------------------- recording

    def _record(self, held: List[Tuple[str, int]], site: Tuple[str, int]):
        with self._graph_mu:
            self.sites.add(site)
            for src in held:
                if src != site:
                    key = (src, site)
                    self.edges[key] = self.edges.get(key, 0) + 1

    # ----------------------------------------------------------- install/undo

    def install(self):
        global _active
        if self._installed:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        san = self

        def make_lock():
            lk = _InstrumentedLock(san._orig_lock(), _caller_site())
            with san._graph_mu:
                san.sites.add(lk._site)
            return lk

        def make_rlock():
            lk = _InstrumentedLock(san._orig_rlock(), _caller_site())
            with san._graph_mu:
                san.sites.add(lk._site)
            return lk

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._installed = True
        _active = self
        return self

    def uninstall(self):
        global _active
        if not self._installed:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        self._installed = False
        if _active is self:
            _active = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    # -------------------------------------------------------------- reporting

    def observed_edges(self) -> List[Tuple[Tuple[str, int], Tuple[str, int]]]:
        with self._graph_mu:
            return sorted(self.edges)

    def cycles(self) -> List[List[Tuple[str, int]]]:
        """Cyclic lock orderings observed at runtime. Any cycle here is a
        potential deadlock: two threads interleaving those paths wedge.
        Uses the same cycle enumeration (core.find_cycles) as the static
        ``lock-order-cycle`` checker, so the two halves cannot diverge on
        what counts as a cycle (``_on_acquire`` never records self-edges)."""
        with self._graph_mu:
            adj: Dict[Tuple[str, int], List] = {}
            for (src, dst) in self.edges:
                adj.setdefault(src, []).append(dst)
        return find_cycles(adj)

    def assert_no_cycles(self):
        cyc = self.cycles()
        if cyc:
            lines = [
                " -> ".join(f"{f}:{ln}" for (f, ln) in c + [c[0]])
                for c in cyc
            ]
            raise AssertionError(
                "lock-order cycles observed at runtime:\n" + "\n".join(lines)
            )

    def site_for_line(self, filename_suffix: str, lineno: Optional[int] = None):
        """Find a recorded allocation site by file suffix (+ line), for
        mapping observed sites back to static lock nodes in tests."""
        with self._graph_mu:
            for (fn, ln) in self.sites:
                if fn.endswith(filename_suffix) and (
                    lineno is None or ln == lineno
                ):
                    return (fn, ln)
        return None
