"""Runtime lock instrumentation layer + lock-order sanitizer.

This module owns THE one instrumentation seam for ``threading`` sync
primitives: ``add_listener()`` monkeypatches the ``threading.Lock`` /
``threading.RLock`` / ``threading.Condition`` factories (refcounted —
restored when the last listener leaves) so every lock allocated
afterwards is wrapped in an instrumented shim. The shim maintains a
per-OS-thread held-lock stack shared by every listener and notifies the
registered listeners on create/acquire/release. Two sanitizers ride the
same seam:

- :class:`LockOrderSanitizer` (here): records ``held -> acquiring``
  edges into a global order graph keyed by the lock's *allocation site*
  (file:line) — the runtime analogue of the static ``lock-order-cycle``
  checker's ``Class.attr`` nodes. ``cycles()`` reports any cyclic
  ordering actually observed (tests opt in via the ``lock_sanitizer``
  conftest fixture).
- :class:`ray_tpu.analysis.racer.RaceSanitizer`: consumes the same
  acquire/release callbacks as happens-before release/acquire edges for
  its vector clocks, and reads the shared held stack for the lock set
  it attaches to every access report.

``threading.Condition`` participates fully: the factory wraps the
implicit ``RLock()`` a bare ``Condition()`` allocates, and the shim
implements ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` so
``Condition.wait()``'s hidden release+reacquire maintains the held
stack and fires listener callbacks like any other release/acquire —
a Condition-vs-Lock order inversion is visible, and the racer sees
``wait()`` as the release/acquire pair it really is. (For a Condition
built on a plain ``Lock``, CPython's own fallback routes through the
shim's instrumented ``acquire``/``release``.)

Internal sanitizer locks are allocated with ``_thread.allocate_lock``
directly — never through the (possibly patched) factories — so listener
callbacks can take them without re-entering the instrumentation.
"""

from __future__ import annotations

import _thread
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.core import find_cycles

_THIS_DIR = __file__.rsplit("sanitizer.py", 1)[0]

# ------------------------------------------------------------------ seam
#
# Module-level state. uninstalling cannot unwrap locks that were already
# handed out, so a shim may outlive the listener set that existed when it
# was created; every notification therefore routes through the CURRENT
# listener tuple (else an inversion between an old-wrapped and a
# new-wrapped lock lands in neither graph), and the per-thread held stack
# is shared so cross-install nestings are seen at all.

_listeners: Tuple[object, ...] = ()
_listeners_mu = _thread.allocate_lock()
_orig_factories: Optional[Tuple] = None  # (Lock, RLock, Condition)
_held_tls = threading.local()


def _held_stack() -> List[Tuple]:
    """Per-thread stack of (site, shim) pairs currently held."""
    st = getattr(_held_tls, "stack", None)
    if st is None:
        st = _held_tls.stack = []
    return st


def held_sites() -> Tuple[Tuple[str, int], ...]:
    """The current thread's held-lock allocation sites, outermost first
    (the lock set the racer stamps onto each access report)."""
    return tuple(site for site, _lk in _held_stack())


def _caller_site(depth: int = 2) -> Tuple[str, int]:
    """Allocation site of the lock: first frame outside this module and
    outside threading.py (Condition() allocates an RLock internally)."""
    f = sys._getframe(depth)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_THIS_DIR) and not fn.endswith("threading.py"):
            return (fn, f.f_lineno)
        f = f.f_back
    return ("<unknown>", 0)


def add_listener(listener) -> None:
    """Register a listener (optional methods: ``on_lock_created(lock,
    site)``, ``on_acquire(lock, site, held)`` — *held* is the site list
    BEFORE this acquisition is pushed — ``on_release(lock, site)``,
    and the blocked-waiter pair ``on_acquire_begin(lock, site)`` /
    ``on_acquire_abort(lock, site)``: *begin* fires BEFORE a blocking
    acquire parks, *abort* fires if that acquire then fails or times
    out, and a successful one resolves through ``on_acquire`` as usual —
    the wait-graph sanitizer needs the begin edge because a deadlocked
    thread, by definition, never reaches ``on_acquire``).
    The first listener installs the factory patches."""
    global _listeners, _orig_factories
    with _listeners_mu:
        if listener in _listeners:
            return
        if not _listeners:
            _orig_factories = (
                threading.Lock, threading.RLock, threading.Condition
            )
            threading.Lock = _make_lock
            threading.RLock = _make_rlock
            threading.Condition = _make_condition
        _listeners = _listeners + (listener,)


def remove_listener(listener) -> None:
    """Unregister; the last listener out restores the real factories."""
    global _listeners, _orig_factories
    with _listeners_mu:
        if listener not in _listeners:
            return
        _listeners = tuple(l for l in _listeners if l is not listener)
        if not _listeners and _orig_factories is not None:
            (threading.Lock, threading.RLock,
             threading.Condition) = _orig_factories
            _orig_factories = None


def _real_factories() -> Tuple:
    """The unpatched (Lock, RLock, Condition), whether or not the seam
    is currently installed."""
    with _listeners_mu:
        if _orig_factories is not None:
            return _orig_factories
    return (threading.Lock, threading.RLock, threading.Condition)


def _make_lock():
    lk = _InstrumentedLock(_real_factories()[0](), _caller_site())
    _notify_created(lk)
    return lk


def _make_rlock():
    lk = _InstrumentedLock(_real_factories()[1](), _caller_site())
    _notify_created(lk)
    return lk


def _make_condition(lock=None):
    """Condition factory: a bare ``Condition()`` gets a WRAPPED RLock
    (CPython would allocate a raw one through its module-local
    ``RLock`` name, bypassing the patched factory), then the real
    Condition class binds the shim's ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` so wait/notify stay
    instrumented."""
    if lock is None:
        lock = _InstrumentedLock(_real_factories()[1](), _caller_site())
        _notify_created(lock)
    return _real_factories()[2](lock)


def _notify_created(lk: "_InstrumentedLock") -> None:
    for lst in _listeners:
        fn = getattr(lst, "on_lock_created", None)
        if fn is not None:
            fn(lk, lk._site)


class _InstrumentedLock:
    """Wraps a real Lock/RLock; maintains the shared held stack and
    notifies the module's CURRENT listeners (not necessarily the ones
    alive when it was wrapped) on acquire/release."""

    def __init__(self, inner, site: Tuple[str, int]):
        self._inner = inner
        self._site = site

    # -------------------------------------------------- notify helpers

    def _notify_acquired(self):
        held = _held_stack()
        for lst in _listeners:
            fn = getattr(lst, "on_acquire", None)
            if fn is not None:
                fn(self, self._site, [s for s, _lk in held])
        held.append((self._site, self))

    def _notify_acquire_begin(self):
        for lst in _listeners:
            fn = getattr(lst, "on_acquire_begin", None)
            if fn is not None:
                fn(self, self._site)

    def _notify_acquire_abort(self):
        for lst in _listeners:
            fn = getattr(lst, "on_acquire_abort", None)
            if fn is not None:
                fn(self, self._site)

    def _notify_releasing(self):
        held = _held_stack()
        # Locks are usually released LIFO; tolerate out-of-order release.
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        for lst in _listeners:
            fn = getattr(lst, "on_release", None)
            if fn is not None:
                fn(self, self._site)

    # ------------------------------------------------------ Lock proto

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # begin fires only for acquires that can PARK: a deadlocked
        # thread never returns from the inner acquire, so a post-hoc
        # on_acquire can never see it — the wait edge must precede it
        began = blocking
        if began:
            self._notify_acquire_begin()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._notify_acquired()
        elif began:
            self._notify_acquire_abort()
        return ok

    def release(self):
        self._notify_releasing()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # Condition binds these three at construction when the lock has
        # them (RLock does; plain Lock raises AttributeError here and
        # Condition falls back to calling our instrumented
        # acquire/release). wait()'s hidden release/reacquire must
        # maintain the held stack and fire listeners, or a Condition
        # order inversion is invisible and the racer misses the
        # happens-before edge wait/notify really is.
        inner = object.__getattribute__(self, "_inner")
        val = getattr(inner, name)  # AttributeError falls through
        if name == "_release_save":
            def _release_save():
                self._notify_releasing()
                return val()
            return _release_save
        if name == "_acquire_restore":
            def _acquire_restore(state):
                # Condition.wait's hidden reacquire can park behind the
                # notifier: the begin/acquired pair makes that wait
                # visible to the wait-graph listener too
                self._notify_acquire_begin()
                val(state)
                self._notify_acquired()
            return _acquire_restore
        return val


class LockOrderSanitizer:
    """ThreadSanitizer-style lock-order recorder (one listener on the
    shared instrumentation seam)."""

    def __init__(self):
        # raw lock: _record runs inside listener callbacks; a wrapped
        # lock here would recurse into the seam
        self._graph_mu = _thread.allocate_lock()
        # (src_site, dst_site) -> observation count
        self.edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]], int] = {}
        self.sites: Set[Tuple[str, int]] = set()
        self._installed = False

    # --------------------------------------------------- seam listener

    def on_lock_created(self, lock, site):
        with self._graph_mu:
            self.sites.add(site)

    def on_acquire(self, lock, site, held):
        with self._graph_mu:
            self.sites.add(site)
            for src in held:
                if src != site:
                    key = (src, site)
                    self.edges[key] = self.edges.get(key, 0) + 1

    # ----------------------------------------------------- install/undo

    def install(self):
        if not self._installed:
            add_listener(self)
            self._installed = True
        return self

    def uninstall(self):
        if self._installed:
            remove_listener(self)
            self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    # -------------------------------------------------------- reporting

    def observed_edges(self) -> List[Tuple[Tuple[str, int], Tuple[str, int]]]:
        with self._graph_mu:
            return sorted(self.edges)

    def cycles(self) -> List[List[Tuple[str, int]]]:
        """Cyclic lock orderings observed at runtime. Any cycle here is a
        potential deadlock: two threads interleaving those paths wedge.
        Uses the same cycle enumeration (core.find_cycles) as the static
        ``lock-order-cycle`` checker, so the two halves cannot diverge on
        what counts as a cycle (``on_acquire`` never records self-edges)."""
        with self._graph_mu:
            adj: Dict[Tuple[str, int], List] = {}
            for (src, dst) in self.edges:
                adj.setdefault(src, []).append(dst)
        return find_cycles(adj)

    def assert_no_cycles(self):
        cyc = self.cycles()
        if cyc:
            lines = [
                " -> ".join(f"{f}:{ln}" for (f, ln) in c + [c[0]])
                for c in cyc
            ]
            raise AssertionError(
                "lock-order cycles observed at runtime:\n" + "\n".join(lines)
            )

    def site_for_line(self, filename_suffix: str, lineno: Optional[int] = None):
        """Find a recorded allocation site by file suffix (+ line), for
        mapping observed sites back to static lock nodes in tests."""
        with self._graph_mu:
            for (fn, ln) in self.sites:
                if fn.endswith(filename_suffix) and (
                    lineno is None or ln == lineno
                ):
                    return (fn, ln)
        return None
