"""ray_tpu.analysis — distributed-correctness linter + runtime sanitizers.

Static half: ``python -m ray_tpu.analysis <paths>`` runs the AST checkers
registered in :mod:`ray_tpu.analysis.checkers` (blocking-in-async,
unsafe-closure-capture, lock-order-cycle, unawaited-coroutine,
dropped-object-ref, resource-spec-validation, unbounded-rpc-call, plus
the protocol checkers over :mod:`ray_tpu.analysis.protocol`'s extracted
RPC model: rpc-method-unknown, rpc-payload-key-mismatch,
push-topic-unknown, config-key-unknown, and the lifecycle checkers over
:mod:`ray_tpu.analysis.statemachine`'s declared/extracted state
machines: illegal-state-transition, cross-thread-field-write, and the
blocking-graph checkers over :mod:`ray_tpu.analysis.waitgraph`:
blocking-wait-under-lock, rpc-reentry-cycle) with per-line
``# ray-lint: disable=<check>`` pragmas and a committed ratchet
baseline. ``--dump-protocol`` emits the protocol model (including the
state machines) as JSON; ``--dump-waitgraph`` emits the interprocedural
blocking graph (execution contexts -> blocking sites -> cross-process
RPC edges) whose cycles are potential distributed deadlocks.

Runtime half: :mod:`ray_tpu.analysis.sanitizer` is the shared lock
instrumentation seam (refcounted ``Lock``/``RLock``/``Condition``
factory patches, one per-thread held stack, listener callbacks) with
:class:`~ray_tpu.analysis.sanitizer.LockOrderSanitizer` riding it
(cross-checking the static lock graph via the ``lock_sanitizer``
fixture); :mod:`ray_tpu.analysis.invariants` (Lamport-clocked protocol
tracer + offline happens-before invariant checker,
``invariant_sanitizer`` fixture / ``--check-trace``); and
:mod:`ray_tpu.analysis.racer` — the hybrid data-race sanitizer: the
``cross-thread-field-write`` model emitted as a machine-readable
watchlist (``--dump-watchlist``) and *validated* by a FastTrack-style
vector-clock engine over the live control-plane threads
(``race_sanitizer`` fixture / ``--race`` / ``chaos_soak --race``;
seeded regression teeth in ``node_daemon.SEEDED_BUGS`` +
``serve.fastpath.SEEDED_BUGS``); and
:mod:`ray_tpu.analysis.waitgraph`'s ``WaitSanitizer`` — the hybrid
wait-for deadlock & stall sanitizer: every lock/queue/future/condition
wait, RPC awaiting a reply, and dag-channel slow-tier park becomes a
node in a live cross-thread AND cross-process wait-for graph, probed
for cycles (deadlock reports carry both stacks + held sets + the RPC
chain) and scanned for stalls by a watchdog (``wait_sanitizer``
fixture / ``--wait`` / ``chaos_soak --stall`` / ``ray_tpu stacks``;
seeded teeth in ``gcs.SEEDED_BUGS`` + ``dag.compiled.SEEDED_BUGS``) —
each runtime sanitizer is the dynamic cross-check of its static model,
and the racer reports a race on a statically-credited-locked field as
a finding against the static analysis itself.

Model-checking half: :mod:`ray_tpu.analysis.explore` runs the real GCS
handler object under a virtual runtime and *searches* handler
interleavings (bounded DFS + pruning + seeded sampling), replaying each
schedule through the invariant checker; ``--explore`` / ``--replay`` on
the CLI, budgeted in CI via ``scripts/lint_gate.py --explore``.
:mod:`ray_tpu.analysis.memmodel` gives the compiled-DAG seqlock channel
the same treatment at word-operation granularity (``--memmodel``,
``lint_gate --memmodel``), kept honest by an op-sequence round-trip
gate against ``dag/channel.py`` plus the two ``chan-*`` checkers
(raw-header-access discipline, publication order).

Deliberately imports no runtime module (jax, numpy, the cluster stack):
linting must work in any environment the source parses in.
"""

from ray_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    AnalysisResult,
    Checker,
    Finding,
    ModuleContext,
    analyze_paths,
    load_baseline,
    register,
    split_by_baseline,
    write_baseline,
)
