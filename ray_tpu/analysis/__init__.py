"""ray_tpu.analysis — distributed-correctness linter + concurrency sanitizer.

Static half: ``python -m ray_tpu.analysis <paths>`` runs the AST checkers
registered in :mod:`ray_tpu.analysis.checkers` (blocking-in-async,
unsafe-closure-capture, lock-order-cycle, unawaited-coroutine,
dropped-object-ref, resource-spec-validation) with per-line
``# ray-lint: disable=<check>`` pragmas and a committed ratchet baseline.

Runtime half: :class:`ray_tpu.analysis.sanitizer.LockOrderSanitizer`, an
instrumented-lock shim recording observed lock orderings (opt in from
tests via the ``lock_sanitizer`` fixture) to cross-check the static graph.

Deliberately imports no runtime module (jax, numpy, the cluster stack):
linting must work in any environment the source parses in.
"""

from ray_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    AnalysisResult,
    Checker,
    Finding,
    ModuleContext,
    analyze_paths,
    load_baseline,
    register,
    split_by_baseline,
    write_baseline,
)
