"""Static blocking-cycle analysis + distributed wait-for deadlock & stall
sanitizer.

Every other verification layer in the tree checks *safety* (invariants,
races, memory-model ordering, RPC budgets); this one checks *liveness*.
Two halves, one tool:

**Static half.** :func:`build_waitgraph` reuses rpcflow's
interprocedural ``_FuncIndex`` machinery over ``cluster/`` + ``serve/``
+ ``dag/`` to extract a *blocking graph*: nodes are execution contexts
(rpc handlers, background threads, subscriber callbacks — the same
roots the ``cross-thread-field-write`` checker derives), annotated with
every blocking site reachable from them (``.call()`` RPCs, chained
``call_async(...).result()``, bare ``Future.result``, ``queue.get``,
``Condition.wait``, ``Thread.join``, ``Channel.read/write``); edges are
"context A blocks on a resource released by context B", where the
cross-PROCESS edges come from the protocol index — a blocking
``.call("m")`` edges into every ``rpc_m`` handler context on the server
that implements it. ``core.find_cycles`` over that graph reports
potential distributed deadlocks, and :func:`reentry_chains` feeds the
``rpc-reentry-cycle`` checker (a handler whose blocking RPC chain can
re-enter its own server class — the GCS→daemon→GCS shape that exhausts
dispatcher threads). The sibling ``blocking-wait-under-lock`` checker
generalizes ``rpc-under-lock`` to every blocking kind classified here.

**Dynamic half.** :class:`WaitSanitizer` rides the SAME instrumentation
seams the racer does — ``sanitizer.add_listener`` for lock
acquire/release (plus the blocked-waiter ``on_acquire_begin`` /
``on_acquire_abort`` pair: a deadlocked thread never reaches
``on_acquire``, so the wait edge must precede the park), its own
``queue.get`` / ``Future.result`` / ``Condition.wait`` / executor
``submit`` patches, the ``rpc.TRACE`` send/recv hooks (it is a
delegating TRACE shim exactly like rpcflow's profiler), and the channel
layer's ``PARKWATCH`` park-begin/park-end stamps — to maintain a live
cross-thread AND cross-process wait-for graph. An in-flight blocking
RPC is a wait edge from the caller thread to the server's handler
context (stitched through ``on_send``/``on_recv`` the same way the
invariant tracer Lamport-stitches). Owners are resolved LAZILY at
cycle-walk time (who holds the lock *now*, which thread is the server
loop *now*), so checking for a cycle only on wait-ENTER is sufficient
and order-insensitive. A cycle fires a deadlock report with BOTH
stacks (``sys._current_frames``), both held-lock sets, and the
in-flight RPC chain; a stall watchdog attributes any wait older than
``stall_warn_s`` (what it waits on, who holds it, for how long —
channel waits name the channel, its peer end's pid and the last
committed seq) into ``artifacts/waitgraph-*.jsonl`` flight-recorder
artifacts. Uninstalled, the ``WAITGRAPH is None`` module-global gate
means product code never consults it (``CONSULTS`` stays 0,
test-asserted) — the rpc.CHAOS / rpc.TRACE / racer.RACER pattern.

Seeded regression teeth live in ``gcs.SEEDED_BUGS``
(``stream-ack-under-lock``: a blocking GCS→daemon call re-introduced
UNDER the GCS lock) and ``compiled.SEEDED_BUGS``
(``chan-read-under-lock``: an output-channel read parked under the DAG
lifecycle lock) — :data:`SEEDED_WAITS` is the one table the CLI,
lint_gate and tests share; each must be caught statically (pragma-
stripped rescan) AND dynamically within ``run_probe``'s rounds.

Known limits (documented, test-pinned): a ``call_async`` whose future
is ``.result()``-ed in a *different* statement resolves statically to a
plain ``future-result`` (no RPC edge — the target method string is not
tracked through the variable); queue/condition waits have no single
releaser, so they get stall attribution but no owner edge (an idle
consumer parked on ``queue.get`` is not a deadlock); dynamic RPC edges
point at the server's *handler loop thread*, which over-approximates
when the loop is busy with an unrelated request — a reported cycle
still requires every thread on it to be genuinely blocked.
"""

from __future__ import annotations

import _thread
import ast
import importlib
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.analysis import protocol as _protocol
from ray_tpu.analysis import sanitizer as _san
from ray_tpu.analysis.core import find_cycles, iter_modules
from ray_tpu.analysis.rpcflow import _MAX_DEPTH, _FuncIndex

#: THE module global (rpc.CHAOS / rpc.TRACE / racer.RACER pattern):
#: ``None`` = no wait sanitizer installed anywhere, and — because
#: installation is what creates the patches — nothing to consult.
WAITGRAPH: Optional["WaitSanitizer"] = None

#: instrumentation consult counter (seam callbacks, runtime patches,
#: TRACE hooks, channel park stamps). The uninstalled-zero-overhead
#: contract is asserted on this.
CONSULTS = 0

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_THIS_DIR = __file__.rsplit("waitgraph.py", 1)[0]

#: static scan scope: the thread-dense control-plane packages
SCAN_SEGMENTS = ("cluster", "serve", "dag")

#: (seeded-bug name, module with the SEEDED_BUGS set, probe that must
#: catch it) — the one table the CLI, lint_gate and tests share.
SEEDED_WAITS = (
    ("stream-ack-under-lock", "ray_tpu.cluster.gcs",
     "gcs-stream-ack-reentry"),
    ("chan-read-under-lock", "ray_tpu.dag.compiled",
     "dag-read-under-lock"),
)


# =====================================================================
# Static half: blocking-site classification + the blocking graph
# =====================================================================

#: kinds the ``blocking-wait-under-lock`` checker flags. ``rpc-call``
#: is deliberately absent: a bare blocking ``.call`` under a lock is
#: ``rpc-under-lock``'s finding, and double-reporting one site under
#: two names would make the baseline discipline ambiguous.
WAIT_KINDS_UNDER_LOCK = (
    "rpc-result", "future-result", "cond-wait", "queue-get",
    "thread-join", "chan-read", "chan-write",
)


def blocking_wait_kind(call: ast.Call) -> Optional[Tuple[str, Optional[str]]]:
    """Classify a call node as a blocking-wait site.

    Returns ``(kind, rpc_method)`` or ``None``; *rpc_method* is the
    string-literal method for ``rpc-call`` / ``rpc-result`` (the kinds
    that grow cross-process edges) and ``None`` otherwise. Kinds:

    - ``rpc-call``:      ``x.call("m", ...)`` (blocking round trip)
    - ``rpc-result``:    ``x.call_async("m", ...).result(...)`` chained
      in one expression — the same round trip spelled in two steps
    - ``future-result``: ``f.result()`` / ``f.result(timeout=...)``
    - ``cond-wait``:     ``cv.wait()`` / ``cv.wait(t)`` (also Event)
    - ``queue-get``:     ``q.get(...)`` with no positional key (which
      excludes ``dict.get(k)``)
    - ``thread-join``:   ``t.join()`` with no positionals (excludes
      ``sep.join(parts)``)
    - ``chan-read`` / ``chan-write``: ``.read`` / ``.write`` carrying a
      ``timeout=`` or ``should_stop=`` keyword — the channel-layer wait
      signature (a bare file ``.read()`` never does)
    """
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    args = call.args
    kwargs = {kw.arg for kw in call.keywords if kw.arg}
    if attr == "call":
        if args and isinstance(args[0], ast.Constant) \
                and isinstance(args[0].value, str):
            return ("rpc-call", args[0].value)
        return None
    if attr == "result":
        inner = f.value
        if isinstance(inner, ast.Call) \
                and isinstance(inner.func, ast.Attribute) \
                and inner.func.attr == "call_async" \
                and inner.args \
                and isinstance(inner.args[0], ast.Constant) \
                and isinstance(inner.args[0].value, str):
            return ("rpc-result", inner.args[0].value)
        if not args:
            return ("future-result", None)
        return None
    if attr == "wait" and len(args) <= 1 and kwargs <= {"timeout"}:
        # extra keywords (num_returns=..., fetch_local=...) mean a
        # result-collection API like ray_tpu.wait, not a condition park
        return ("cond-wait", None)
    if attr == "get" and not args:
        return ("queue-get", None)
    if attr == "join" and not args:
        return ("thread-join", None)
    if attr in ("read", "write") and (kwargs & {"timeout", "should_stop"}):
        return ("chan-read" if attr == "read" else "chan-write", None)
    return None


@dataclass
class BlockSite:
    """One blocking wait reachable from a context root."""

    path: str                   # repo-relative module path
    line: int
    kind: str                   # one of the blocking_wait_kind kinds
    method: Optional[str]       # rpc method for rpc-call / rpc-result
    via: Tuple[str, ...]        # same-class/module call chain from root
    end_line: int = 0           # last physical line (pragma range)

    def to_dict(self) -> Dict[str, Any]:
        return {"path": self.path, "line": self.line, "kind": self.kind,
                "method": self.method, "via": list(self.via)}


def _executor_offloaded(fn) -> Set[int]:
    """ids of AST nodes inside a lambda handed to ``run_in_executor``:
    that code runs on the EXECUTOR context (which ``_context_roots``
    walks as its own root), not on the enclosing handler — a handler
    that offloads its blocking work and returns the future does not
    block the dispatcher, so charging the lambda's waits to the handler
    would fabricate reentry cycles (the daemon's object-pull shape)."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "run_in_executor":
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    for sub in ast.walk(arg):
                        out.add(id(sub))
    return out


def _is_seeded_test(test) -> bool:
    """``"bug" in SEEDED_BUGS`` (possibly one conjunct of an ``and``)."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        return any(_is_seeded_test(v) for v in test.values)
    return (isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.In)
            and any(isinstance(c, ast.Name) and c.id == "SEEDED_BUGS"
                    for c in test.comparators))


def _seeded_gated(fn) -> Set[int]:
    """ids of AST nodes inside an ``if "..." in SEEDED_BUGS:`` body:
    the seeded teeth only run when a test arms them, so the blocking
    graph models the NORMAL path (memmodel's ``_seeded_branch_kind``
    rule). The teeth are still proven statically by the gate's
    pragma-stripped ``blocking-wait-under-lock`` rescan — through the
    checker, not the graph."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.If) and _is_seeded_test(node.test):
            for sub in node.body:
                for n in ast.walk(sub):
                    out.add(id(n))
    return out


class _BlockWalker:
    """Collect every blocking site reachable from a root function by
    following rpcflow's call resolution (same-class ``self.m()``, bare
    module functions, unique repo-wide methods), depth-capped like the
    rpc-cost walker. Blocking calls are classified FIRST — a ``.call``
    is a site, never an edge to some unrelated ``call`` method."""

    def __init__(self, index: _FuncIndex):
        self.index = index

    def walk(self, root) -> List[BlockSite]:
        sites: List[BlockSite] = []
        seen: Set[Tuple] = set()

        def visit(info, chain: Tuple[str, ...]) -> None:
            if info.key in seen or len(chain) > _MAX_DEPTH:
                return
            seen.add(info.key)
            skipped = _executor_offloaded(info.node)
            skipped |= _seeded_gated(info.node)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call) \
                        or id(node) in skipped:
                    continue
                kind = blocking_wait_kind(node)
                if kind is not None:
                    sites.append(BlockSite(
                        path=info.relpath, line=node.lineno,
                        kind=kind[0], method=kind[1], via=chain,
                        end_line=getattr(node, "end_lineno", 0) or 0,
                    ))
                    continue
                callee = self.index.resolve_call(node, info)
                if callee is not None:
                    visit(callee, chain + (callee.name,))

        visit(root, ())
        return sites


@dataclass
class WaitGraphReport:
    """The static blocking graph: context label -> blocking sites, RPC
    edges between contexts, and the cycles found over them."""

    root: str
    contexts: Dict[str, List[BlockSite]]
    edges: Dict[Tuple[str, str], BlockSite]
    cycles: List[List[str]]

    def adjacency(self) -> Dict[str, List[str]]:
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        return adj

    def to_dict(self) -> Dict[str, Any]:
        return {
            "contexts": {
                label: [s.to_dict() for s in sites]
                for label, sites in sorted(self.contexts.items())
            },
            "edges": [
                {"src": src, "dst": dst, "path": site.path,
                 "line": site.line, "kind": site.kind,
                 "method": site.method}
                for (src, dst), site in sorted(self.edges.items())
            ],
            "cycles": [list(c) for c in self.cycles],
        }


def _is_control_plane(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return bool(set(parts[:-1]) & set(SCAN_SEGMENTS))


def _is_handler_label(label: str) -> bool:
    """Handler contexts are ``server.rpc_m``; thread/subscriber roots
    carry the class (``server.Cls.meth``) so the two never collide."""
    parts = label.split(".")
    return len(parts) == 2 and parts[1].startswith(
        _protocol.HANDLER_PREFIX)


def build_from_contexts(ctxs: Sequence, root: str) -> WaitGraphReport:
    """Build the blocking graph from already-parsed ModuleContexts (the
    ``rpc-reentry-cycle`` checker path: the lint pass parsed everything
    once; reparsing would double the cost of the whole run). Every
    module is indexed — helpers outside the control plane still resolve
    — but context roots come only from control-plane modules."""
    from ray_tpu.analysis.checkers import CrossThreadFieldWriteChecker

    index = _FuncIndex()
    proto = _protocol.ProtocolIndex()
    for ctx in ctxs:
        index.add_module(ctx)
        proto.merge(_protocol.ProtocolIndex.piece_for(ctx))

    helper = CrossThreadFieldWriteChecker()
    walker = _BlockWalker(index)
    contexts: Dict[str, List[BlockSite]] = {}
    for ctx in ctxs:
        rel = ctx.relpath.replace("\\", "/")
        if not _is_control_plane(rel):
            continue
        server = _protocol._server_label(rel)
        for cls in ctx.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for meth, _desc in helper._context_roots(cls, methods):
                if meth not in methods:
                    continue
                label = (f"{server}.{meth}"
                         if meth.startswith(_protocol.HANDLER_PREFIX)
                         else f"{server}.{cls.name}.{meth}")
                if label in contexts:
                    continue
                info = index.lookup(rel, cls.name, meth)
                if info is not None:
                    contexts[label] = walker.walk(info)

    # cross-process RPC edges: a blocking call with method m edges into
    # every rpc_m handler context reachable through the protocol index
    edges: Dict[Tuple[str, str], BlockSite] = {}
    for label, sites in contexts.items():
        for s in sites:
            if s.kind not in ("rpc-call", "rpc-result") or not s.method:
                continue
            for h in proto.handlers.get(s.method, ()):
                dst = f"{h.server}.{_protocol.HANDLER_PREFIX}{s.method}"
                if dst in contexts and (label, dst) not in edges:
                    edges[(label, dst)] = s

    adj: Dict[str, List[str]] = {}
    for (src, dst) in edges:
        adj.setdefault(src, []).append(dst)
    return WaitGraphReport(root=root, contexts=contexts, edges=edges,
                           cycles=find_cycles(adj))


def build_waitgraph(paths: Optional[Sequence[str]] = None,
                    root: Optional[str] = None) -> WaitGraphReport:
    """Build the blocking graph for the control plane (or an explicit
    path set). Raises on unparseable input — a silently partial graph
    would make the cycle scan pass vacuously (same contract as
    ``extract_protocol``)."""
    root = root or _REPO
    if paths is None:
        paths = [os.path.join(root, "ray_tpu", seg)
                 for seg in SCAN_SEGMENTS]
    errors: List[str] = []
    ctxs = list(iter_modules(paths, root=root, errors=errors))
    if errors:
        raise ValueError(
            "build_waitgraph: unparseable file(s): " + "; ".join(errors)
        )
    return build_from_contexts(ctxs, root)


def reentry_chains(report: WaitGraphReport) -> List[Dict[str, Any]]:
    """Handler contexts whose blocking RPC closure re-enters their own
    server (including the 1-hop self-call): each entry carries the
    originating handler, the context chain, and the first blocking site
    on the offending path — the line the ``rpc-reentry-cycle`` checker
    anchors its finding to."""
    adj = report.adjacency()
    out: List[Dict[str, Any]] = []
    seen: Set[Tuple] = set()
    for origin in sorted(report.contexts):
        if not _is_handler_label(origin):
            continue
        server = origin.split(".", 1)[0]
        stack: List[Tuple[str, Tuple[str, ...]]] = [(origin, (origin,))]
        visited: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if _is_handler_label(nxt) \
                        and nxt.split(".", 1)[0] == server:
                    chain = path + (nxt,)
                    key = (origin, frozenset(chain))
                    if key not in seen:
                        seen.add(key)
                        first_hop = path[1] if len(path) > 1 else nxt
                        out.append({
                            "origin": origin,
                            "chain": list(chain),
                            "site": report.edges[(origin, first_hop)],
                        })
                    continue
                if nxt not in visited and len(path) < 8:
                    visited.add(nxt)
                    stack.append((nxt, path + (nxt,)))
    return out


# =====================================================================
# Dynamic half: the wait-for sanitizer
# =====================================================================


def _is_rlock(lock) -> bool:
    """Reentrant? (an owner re-acquiring an RLock never parks, so it
    must not grow a wait record — that would be a 1-cycle)."""
    inner = getattr(lock, "_inner", lock)
    return "rlock" in type(inner).__name__.lower()


def _fmt_frames(frame, depth: int) -> List[list]:
    """[relpath, line, func] rows for one live frame, own-machinery
    frames (this module + the seam) elided, innermost last."""
    out: List[list] = []
    for fs in traceback.extract_stack(frame):
        fn = fs.filename
        if fn.startswith(_THIS_DIR) and (
                fn.endswith("waitgraph.py") or fn.endswith("sanitizer.py")):
            continue
        rel = fn[len(_REPO) + 1:] if fn.startswith(_REPO) else fn
        out.append([rel, fs.lineno, fs.name])
    return out[-depth:]


class WaitSanitizer:
    """Live cross-thread + cross-process wait-for graph (see module
    docstring). One instance installs globally (the ``WAITGRAPH``
    module global); a second concurrent install is an error."""

    _dump_seq = 0

    def __init__(self, stall_warn_s: float = 5.0, stack_depth: int = 16,
                 max_reports: int = 32,
                 watchdog_interval_s: Optional[float] = None):
        # raw lock: every method here runs inside instrumentation
        # callbacks; a wrapped lock would recurse into the seam
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._installed = False
        self._inner = None          # wrapped rpc.TRACE (delegation)
        self.stall_warn_s = stall_warn_s
        self.stack_depth = stack_depth
        self.max_reports = max_reports
        self._watch_interval = watchdog_interval_s if \
            watchdog_interval_s is not None else \
            min(1.0, max(0.05, stall_warn_s / 4.0))
        # ---- wait-for state (all under _mu) -------------------------
        self._waits: Dict[int, List[dict]] = {}   # tid -> wait-record stack
        self._held: Dict[int, List[str]] = {}     # tid -> held lock sites
        self._lock_owner: Dict[int, Tuple[int, int]] = {}  # id -> (tid, n)
        self._lock_site: Dict[int, Tuple[str, int]] = {}
        self._srv_thread: Dict[str, int] = {}     # server name -> loop tid
        self._chan_end: Dict[Tuple, int] = {}     # (key, role) -> tid
        self._rpc_stack: Dict[int, deque] = {}    # tid -> in-flight sends
        self._dedup: Set[frozenset] = set()
        self._warned: Set[int] = set()
        self._lc = 0                              # lamport fallback clock
        # ---- results ------------------------------------------------
        self.deadlocks: List[dict] = []
        self.stalls: List[dict] = []
        self._stop = False
        self._watchdog: Optional[threading.Thread] = None

    @property
    def found(self) -> bool:
        return bool(self.deadlocks)

    # ------------------------------------------------- install / undo

    def install(self) -> "WaitSanitizer":
        global WAITGRAPH
        if WAITGRAPH is not None:
            raise RuntimeError("a WaitSanitizer is already installed")
        from ray_tpu.cluster import rpc as rpc_mod
        from ray_tpu.dag import channel as chan_mod
        self._inner = rpc_mod.TRACE
        rpc_mod.TRACE = self
        chan_mod.PARKWATCH = self
        WAITGRAPH = self
        _san.add_listener(self)
        _patch_runtime()
        self._installed = True
        self._stop = False
        t = threading.Thread(target=self._watch_loop,
                             name="waitgraph-watchdog", daemon=True)
        self._watchdog = t
        t.start()
        return self

    def uninstall(self) -> None:
        global WAITGRAPH
        if not self._installed:
            return
        from ray_tpu.cluster import rpc as rpc_mod
        from ray_tpu.dag import channel as chan_mod
        self._stop = True
        WAITGRAPH = None
        if chan_mod.PARKWATCH is self:
            chan_mod.PARKWATCH = None
        if rpc_mod.TRACE is self:
            rpc_mod.TRACE = self._inner
        _unpatch_runtime()
        _san.remove_listener(self)
        self._installed = False
        w = self._watchdog
        if w is not None:
            w.join(2.0)
            self._watchdog = None

    def __enter__(self) -> "WaitSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------- plumbing

    def _internal(self) -> bool:
        return bool(getattr(self._tls, "internal", False))

    def _pend(self) -> List[dict]:
        """TLS stack of unresolved acquire-begin records (begin/acquired
        strictly nest per thread, incl. Condition.wait's reacquire)."""
        st = getattr(self._tls, "pend", None)
        if st is None:
            st = self._tls.pend = []
        return st

    @staticmethod
    def _thread_name(tid: int) -> str:
        # NEVER threading.current_thread() here: minting a _DummyThread
        # allocates an instrumented Event -> infinite recursion
        t = threading._active.get(tid)
        return t.name if t is not None else f"tid-{tid}"

    @staticmethod
    def _res_descr(rec: dict) -> str:
        return rec.get("descr") or str(rec.get("res"))

    # ----------------------------------------------- wait-record core

    def _wait_enter(self, reskey: Tuple, descr: str,
                    extra: Optional[dict] = None) -> Optional[dict]:
        """Push a wait record for the current thread and walk for a
        cycle. Lazy owner resolution makes enter-only checking
        sufficient: whichever of two mutually-blocked threads parks
        LAST sees the full cycle."""
        if self._internal():
            return None
        tid = threading.get_ident()
        rec = {"res": reskey, "descr": descr, "tid": tid,
               "t": time.monotonic()}
        if extra:
            rec.update(extra)
        with self._mu:
            self._waits.setdefault(tid, []).append(rec)
            cycle = self._find_cycle_locked(tid)
            if cycle is not None:
                self._report_deadlock_locked(cycle)
        return rec

    def _wait_exit(self, rec: Optional[dict]) -> None:
        if rec is None:
            return
        tid = rec["tid"]
        with self._mu:
            st = self._waits.get(tid)
            if st:
                for i in range(len(st) - 1, -1, -1):
                    if st[i] is rec:
                        del st[i]
                        break
                if not st:
                    self._waits.pop(tid, None)

    def _owner_of_locked(self, rec: dict) -> Optional[int]:
        """Who releases this resource, resolved NOW (under _mu)."""
        res = rec["res"]
        kind = res[0]
        if kind == "lock":
            own = self._lock_owner.get(res[1])
            return own[0] if own else None
        if kind == "rpc-srv":
            tid = self._srv_thread.get(res[1])
            if tid is not None:
                return tid
            box = rec.get("box")
            if box and not box.get("done"):
                return box.get("tid")
            return None
        if kind == "future":
            box = rec.get("box")
            if box and not box.get("done"):
                return box.get("tid")
            return None
        if kind == "chan":
            return self._chan_end.get((res[1], res[2]))
        return None  # queue / cond: no single releaser

    def _resolvable_locked(self, tid: int) -> Tuple[Optional[dict],
                                                    Optional[int]]:
        """The innermost wait record with a resolvable owner. Waits
        NEST: ``Future.result`` / ``queue.get`` park on an internal
        Condition, stacking an ownerless ``cond`` record on top of the
        meaningful ``future``/``rpc-srv``/``queue`` one — a walk that
        only looked at the top of the stack would dead-end there and
        detection would hinge on which side happened to park last."""
        st = self._waits.get(tid)
        if not st:
            return None, None
        for rec in reversed(st):
            owner = self._owner_of_locked(rec)
            if owner is not None:
                return rec, owner
        return st[-1], None

    def _find_cycle_locked(self, start_tid: int) -> Optional[List[dict]]:
        seen: Dict[int, int] = {}
        path: List[dict] = []
        tid = start_tid
        while True:
            if tid in seen:
                return path[seen[tid]:]
            rec, owner = self._resolvable_locked(tid)
            if rec is None or owner is None:
                return None
            seen[tid] = len(path)
            path.append(rec)
            tid = owner

    def _report_deadlock_locked(self, cycle: List[dict]) -> None:
        if len(self.deadlocks) >= self.max_reports:
            return
        key = frozenset(r["res"] for r in cycle)
        if key in self._dedup:
            return
        self._dedup.add(key)
        frames = sys._current_frames()
        threads, chain = [], []
        for r in cycle:
            tid = r["tid"]
            frame = frames.get(tid)
            threads.append({
                "tid": tid,
                "thread": self._thread_name(tid),
                "waiting_on": self._res_descr(r),
                "age_s": round(time.monotonic() - r["t"], 4),
                "held": list(self._held.get(tid, [])),
                "stack": (_fmt_frames(frame, self.stack_depth)
                          if frame is not None else []),
            })
            dq = self._rpc_stack.get(tid)
            if dq:
                for e in dq:
                    chain.append({"src": e["src"], "dst": e["dst"],
                                  "method": e["method"]})
        self.deadlocks.append({
            "kind": "deadlock",
            "pid": os.getpid(),
            "cycle": [self._res_descr(r) for r in cycle],
            "threads": threads,
            "rpc_chain": chain,
        })

    # --------------------------------------- seam listener (lock seam)

    def on_lock_created(self, lock, site) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        with self._mu:
            self._lock_site[id(lock)] = site

    def on_acquire_begin(self, lock, site) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        lid = id(lock)
        me = threading.get_ident()
        with self._mu:
            self._lock_site.setdefault(lid, site)
            own = self._lock_owner.get(lid)
        if own is not None and own[0] == me and _is_rlock(lock):
            return  # reentrant re-acquire never parks
        rec = self._wait_enter(("lock", lid), f"lock {site[0]}:{site[1]}")
        if rec is not None:
            self._pend().append(rec)

    def on_acquire_abort(self, lock, site) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        pend = self._pend()
        if pend and pend[-1]["res"] == ("lock", id(lock)):
            self._wait_exit(pend.pop())

    def on_acquire(self, lock, site, held) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        lid = id(lock)
        me = threading.get_ident()
        pend = self._pend()
        if pend and pend[-1]["res"] == ("lock", lid):
            self._wait_exit(pend.pop())
        with self._mu:
            own = self._lock_owner.get(lid)
            if own is not None and own[0] == me:
                self._lock_owner[lid] = (me, own[1] + 1)
            else:
                self._lock_owner[lid] = (me, 1)
            self._held.setdefault(me, []).append(f"{site[0]}:{site[1]}")

    def on_release(self, lock, site) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        lid = id(lock)
        me = threading.get_ident()
        with self._mu:
            own = self._lock_owner.get(lid)
            if own is not None and own[0] == me:
                if own[1] <= 1:
                    self._lock_owner.pop(lid, None)
                else:
                    self._lock_owner[lid] = (me, own[1] - 1)
            hl = self._held.get(me)
            if hl:
                s = f"{site[0]}:{site[1]}"
                for i in range(len(hl) - 1, -1, -1):
                    if hl[i] == s:
                        del hl[i]
                        break

    # --------------------------------------------- rpc.TRACE delegate

    def on_send(self, src, dst, method):
        inner = self._inner
        if not self._internal():
            global CONSULTS
            CONSULTS += 1
            me = threading.get_ident()
            with self._mu:
                dq = self._rpc_stack.get(me)
                if dq is None:
                    dq = self._rpc_stack[me] = deque(maxlen=8)
                dq.append({"src": src, "dst": dst, "method": method,
                           "t": time.monotonic()})
        if inner is not None:
            return inner.on_send(src, dst, method)
        self._lc += 1
        return self._lc

    def on_send_bytes(self, method, nbytes, kind):
        if not self._internal():
            global CONSULTS
            CONSULTS += 1
            if kind == "notify":
                # a notify never blocks: drop its in-flight entry so it
                # cannot masquerade as the wait target of a later
                # Future.result on this thread
                me = threading.get_ident()
                with self._mu:
                    dq = self._rpc_stack.get(me)
                    if dq and dq[-1]["method"] == method:
                        dq.pop()
        inner = self._inner
        if inner is not None:
            osb = getattr(inner, "on_send_bytes", None)
            if osb is not None:
                return osb(method, nbytes, kind)
        return None

    def on_recv(self, src, dst, method, lc):
        if not self._internal():
            global CONSULTS
            CONSULTS += 1
            with self._mu:
                # fires on the server's loop thread: THE thread an
                # in-flight rpc to `dst` is waiting on
                self._srv_thread[dst] = threading.get_ident()
        inner = self._inner
        if inner is not None:
            return inner.on_recv(src, dst, method, lc)
        return None

    def apply(self, *a, **k):
        inner = self._inner
        return inner.apply(*a, **k) if inner is not None else None

    def merge_clock(self, clock):
        inner = self._inner
        return inner.merge_clock(clock) if inner is not None else None

    def __getattr__(self, name: str):
        # transparent facade: unknown TRACE attrs (is_flight_recorder,
        # ring dumps, ...) resolve against the wrapped tracer
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # ---------------------------------------------- runtime-patch hooks

    def _queue_wait(self, q) -> Optional[dict]:
        global CONSULTS
        if self._internal():
            return None
        CONSULTS += 1
        return self._wait_enter(("queue", id(q)),
                                f"queue.get 0x{id(q):x}")

    def _cond_wait(self, cv) -> Optional[dict]:
        global CONSULTS
        if self._internal():
            return None
        CONSULTS += 1
        return self._wait_enter(("cond", id(cv)),
                                f"condition.wait 0x{id(cv):x}")

    def _future_wait(self, fut) -> Optional[dict]:
        global CONSULTS
        if self._internal():
            return None
        CONSULTS += 1
        if fut.done():
            return None
        me = threading.get_ident()
        box = getattr(fut, "_wg_box", None)
        with self._mu:
            dq = self._rpc_stack.get(me)
            top = dict(dq[-1]) if dq else None
        if top is not None:
            # blocking on the reply to the newest in-flight rpc: the
            # wait edge crosses into the server's handler context
            return self._wait_enter(
                ("rpc-srv", top["dst"]),
                f"rpc {top['src']}->{top['dst']} `{top['method']}`",
                extra={"rpc": top, "box": box},
            )
        return self._wait_enter(("future", id(fut)),
                                f"future.result 0x{id(fut):x}",
                                extra={"box": box})

    def _future_wait_done(self, rec: Optional[dict]) -> None:
        if rec is None:
            return
        self._wait_exit(rec)
        rpc = rec.get("rpc")
        if rpc is not None:
            with self._mu:
                dq = self._rpc_stack.get(rec["tid"])
                if dq:
                    for i in range(len(dq) - 1, -1, -1):
                        if dq[i]["method"] == rpc["method"] \
                                and dq[i]["dst"] == rpc["dst"]:
                            del dq[i]
                            break

    # --------------------------------------- channel PARKWATCH target

    def chan_open(self, ch, role: str) -> None:
        global CONSULTS
        if self._internal():
            return
        CONSULTS += 1
        with self._mu:
            self._chan_end[(ch.key, role)] = threading.get_ident()

    def park_begin(self, ch, op: str) -> Optional[dict]:
        global CONSULTS
        if self._internal():
            return None
        CONSULTS += 1
        role = "writer" if op == "write" else "reader"
        peer = "reader" if op == "write" else "writer"
        with self._mu:
            self._chan_end[(ch.key, role)] = threading.get_ident()
        return self._wait_enter(
            ("chan", ch.key, peer),
            f"channel.{op} `{ch.key}` (peer: {peer})",
            extra={"chan": ch.key, "op": op, "ch": ch},
        )

    def park_end(self, ch, op: str, rec: Optional[dict]) -> None:
        if rec is None:
            return
        self._wait_exit(rec)

    # ------------------------------------------------- stall watchdog

    def _watch_loop(self) -> None:
        # the internal flag makes every own lock/queue/etc op invisible
        # to the instrumentation — the watchdog must never grow wait
        # records of its own
        self._tls.internal = True
        while not self._stop:
            time.sleep(self._watch_interval)
            try:
                self._scan_stalls()
            except Exception:
                # a crashed watchdog would silently disable stall
                # detection for the rest of the run; skip the bad scan
                pass

    def _chan_attribution(self, rec: dict) -> Dict[str, Any]:
        out: Dict[str, Any] = {"key": rec.get("chan"), "op": rec.get("op")}
        ch = rec.get("ch")
        if ch is not None:
            try:
                out.update(ch.wait_state())
                out["peer_pid"] = ch.peer_pid()
            except Exception:
                out["state"] = "unreadable"
        return out

    def _scan_stalls(self) -> None:
        now = time.monotonic()
        stale = []
        with self._mu:
            for tid, st in self._waits.items():
                if not st:
                    continue
                # attribute the OUTERMOST record: it names the
                # API-level wait (future.result, rpc, queue.get) rather
                # than the internal Condition it parks on, and carries
                # the owner/channel attribution the report needs
                rec = st[0]
                age = now - rec["t"]
                if age < self.stall_warn_s or id(rec) in self._warned:
                    continue
                self._warned.add(id(rec))
                stale.append((tid, rec, self._owner_of_locked(rec), age))
            held_snap = {t: list(h) for t, h in self._held.items()}
        if not stale:
            return
        stacks = self.dump_stacks()
        reports = []
        for tid, rec, owner, age in stale:
            holder = None
            if owner is not None:
                holder = {"tid": owner,
                          "thread": self._thread_name(owner),
                          "held": held_snap.get(owner, [])}
            entry: Dict[str, Any] = {
                "tid": tid,
                "thread": self._thread_name(tid),
                "resource": self._res_descr(rec),
                "age_s": round(age, 3),
                "holder": holder,
                # queue/cond waits are idle-consumer shapes, channel
                # waits carry their own attribution below: only a
                # lock/future/rpc wait with NO resolvable owner is a
                # genuinely unattributed stall
                "unattributed": owner is None
                and rec["res"][0] in ("lock", "future", "rpc-srv"),
                "stacks": stacks,
            }
            if rec.get("chan") is not None:
                entry["channel"] = self._chan_attribution(rec)
            reports.append(entry)
        with self._mu:
            self.stalls.extend(reports)
        self.dump("stall")

    # ------------------------------------------------------ reporting

    def dump_stacks(self) -> List[dict]:
        """All-thread stacks annotated with current wait edges and held
        locks (the `ray_tpu stacks` payload)."""
        frames = sys._current_frames()
        with self._mu:
            waits = {t: [self._res_descr(r) for r in st]
                     for t, st in self._waits.items()}
            held = {t: list(h) for t, h in self._held.items()}
        out = []
        for tid in sorted(frames):
            out.append({
                "tid": tid,
                "thread": self._thread_name(tid),
                "waiting_on": waits.get(tid, []),
                "held": held.get(tid, []),
                "stack": _fmt_frames(frames[tid], self.stack_depth),
            })
        return out

    def format_stacks(self, stacks: Optional[List[dict]] = None) -> str:
        stacks = stacks if stacks is not None else self.dump_stacks()
        lines = []
        for e in stacks:
            hdr = f"-- {e['thread']} (tid {e['tid']})"
            if e.get("waiting_on"):
                hdr += f"  WAITING on {e['waiting_on'][-1]}"
            if e.get("held"):
                hdr += f"  holding [{', '.join(e['held'])}]"
            lines.append(hdr)
            for rel, ln, name in e.get("stack", ()):
                lines.append(f"    {rel}:{ln} in {name}")
        return "\n".join(lines)

    def dump(self, reason: str = "report",
             out_dir: Optional[str] = None) -> str:
        """Write the accumulated deadlock + stall reports as a JSONL
        artifact beside the flight recorder's."""
        out_dir = out_dir or os.environ.get("RAY_TPU_FLIGHTREC_DIR",
                                            "artifacts")
        os.makedirs(out_dir, exist_ok=True)
        WaitSanitizer._dump_seq += 1
        path = os.path.join(
            out_dir,
            f"waitgraph-{os.getpid()}-{reason}-{WaitSanitizer._dump_seq}"
            ".jsonl",
        )
        with self._mu:
            deadlocks = list(self.deadlocks)
            stalls = list(self.stalls)
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({
                "kind": "waitgraph-report", "pid": os.getpid(),
                "reason": reason, "deadlocks": len(deadlocks),
                "stalls": len(stalls),
            }) + "\n")
            for d in deadlocks:
                f.write(json.dumps(d) + "\n")
            for s in stalls:
                f.write(json.dumps({"kind": "stall", **s}) + "\n")
        return path

    def dump_stacks_artifact(self, out_dir: Optional[str] = None) -> str:
        """Write an annotated all-thread stack dump artifact (the
        `ray_tpu stacks` collection protocol; also the SIGUSR1 path)."""
        out_dir = out_dir or os.environ.get("RAY_TPU_FLIGHTREC_DIR",
                                            "artifacts")
        os.makedirs(out_dir, exist_ok=True)
        WaitSanitizer._dump_seq += 1
        path = os.path.join(
            out_dir,
            f"waitgraph-{os.getpid()}-stacks-{WaitSanitizer._dump_seq}"
            ".jsonl",
        )
        stacks = self.dump_stacks()
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({"kind": "waitgraph-stacks",
                                "pid": os.getpid()}) + "\n")
            for e in stacks:
                f.write(json.dumps(e) + "\n")
        return path


def install_stack_signal(signum=None) -> None:
    """Install a SIGUSR1 handler that writes a
    ``waitgraph-<pid>-stacks-*.jsonl`` artifact — the collection
    protocol `ray_tpu stacks` drives against every local cluster
    process. Works with no sanitizer installed too: stacks without wait
    annotations are still stacks."""
    import signal

    signum = signum if signum is not None else signal.SIGUSR1

    def _on_sig(_sig, _frame):
        w = WAITGRAPH
        (w if w is not None else WaitSanitizer()).dump_stacks_artifact()

    signal.signal(signum, _on_sig)


# ------------------------------------------------------ runtime patches

_runtime_orig: Optional[dict] = None


def _patch_runtime() -> None:
    """Patch the blocking stdlib waits the lock seam cannot see:
    ``queue.Queue.get``, ``Future.result`` (+ ``submit``, which stamps
    the executing thread into a box so the future's owner resolves),
    and ``wait`` on the REAL Condition class (Event.wait routes through
    it). Every wrapper re-reads the WAITGRAPH global — the racer's
    zero-overhead-when-off pattern — and composes with the racer's own
    patches in LIFO install order."""
    global _runtime_orig
    if _runtime_orig is not None:
        return
    import concurrent.futures as cf
    import queue as queue_mod

    real_cond = _san._real_factories()[2]
    orig = {
        "queue_get": queue_mod.Queue.get,
        "submit": cf.ThreadPoolExecutor.submit,
        "result": cf.Future.result,
        "cond_wait": real_cond.wait,
        "cond_cls": real_cond,
    }

    def get(self, *a, **k):
        w = WAITGRAPH
        if w is None:
            return orig["queue_get"](self, *a, **k)
        rec = w._queue_wait(self)
        try:
            return orig["queue_get"](self, *a, **k)
        finally:
            w2 = WAITGRAPH
            if w2 is not None:
                w2._wait_exit(rec)

    def submit(self, fn, *args, **kwargs):
        w = WAITGRAPH
        if w is None:
            return orig["submit"](self, fn, *args, **kwargs)
        global CONSULTS
        CONSULTS += 1
        box: dict = {}

        def task(*a, **k):
            box["tid"] = threading.get_ident()
            try:
                return fn(*a, **k)
            finally:
                box["done"] = True

        fut = orig["submit"](self, task, *args, **kwargs)
        fut._wg_box = box
        return fut

    def result(self, timeout=None):
        w = WAITGRAPH
        if w is None:
            return orig["result"](self, timeout)
        rec = w._future_wait(self)
        try:
            return orig["result"](self, timeout)
        finally:
            w2 = WAITGRAPH
            if w2 is not None:
                w2._future_wait_done(rec)

    def cond_wait(self, timeout=None):
        w = WAITGRAPH
        if w is None:
            return orig["cond_wait"](self, timeout)
        rec = w._cond_wait(self)
        try:
            return orig["cond_wait"](self, timeout)
        finally:
            w2 = WAITGRAPH
            if w2 is not None:
                w2._wait_exit(rec)

    queue_mod.Queue.get = get
    cf.ThreadPoolExecutor.submit = submit
    cf.Future.result = result
    real_cond.wait = cond_wait
    _runtime_orig = orig


def _unpatch_runtime() -> None:
    global _runtime_orig
    if _runtime_orig is None:
        return
    import concurrent.futures as cf
    import queue as queue_mod

    queue_mod.Queue.get = _runtime_orig["queue_get"]
    cf.ThreadPoolExecutor.submit = _runtime_orig["submit"]
    cf.Future.result = _runtime_orig["result"]
    _runtime_orig["cond_cls"].wait = _runtime_orig["cond_wait"]
    _runtime_orig = None


# =====================================================================
# seeded-bug probes (the regression teeth)
# =====================================================================


class ProbeResult:
    def __init__(self, name: str, seeded: Tuple[str, ...],
                 detected: bool, rounds: int, deadlocks: List[dict],
                 stalls: List[dict]):
        self.name = name
        self.seeded = seeded
        self.detected = detected
        self.rounds = rounds
        self.deadlocks = deadlocks
        self.stalls = stalls

    def summary(self) -> str:
        state = (f"DEADLOCK after {self.rounds} round(s)" if self.detected
                 else f"clean after {self.rounds} round(s)")
        seed = f" [seeded: {','.join(self.seeded)}]" if self.seeded else ""
        return (f"waitgraph:{self.name}: {state}, "
                f"{len(self.deadlocks)} report(s){seed}")


def _probe_gcs_stream_ack(_round: int) -> None:
    """gcs layer: drives the REAL ``rpc_stream_ack`` against a fake
    daemon client whose handler (on a real executor thread) needs the
    GCS lock. Clean code snapshots under the lock and notifies OUTSIDE
    it — no cycle; the seeded ``stream-ack-under-lock`` branch blocks
    on the daemon's reply while HOLDING it: main waits
    rpc-srv(daemon), the daemon worker waits the gcs lock — a
    lock-RPC wait cycle, detected at whichever side parks last."""
    import concurrent.futures as cf

    from ray_tpu.cluster import rpc as rpc_mod
    from ray_tpu.cluster.gcs import GcsServer

    g = object.__new__(GcsServer)
    g._lock = threading.RLock()  # instrumented: allocated under the seam
    g.running = {"t-probe": {"node_id": "n1"}}
    g.nodes = {"n1": {"alive": True, "addr": "127.0.0.1", "port": 0}}

    pool = cf.ThreadPoolExecutor(max_workers=1,
                                 thread_name_prefix="waitprobe-daemon")
    started = threading.Event()

    class _Client:
        """Just enough of a daemon RpcClient for `_daemon_client`'s
        cache hit: sends consult rpc.TRACE exactly like the real client
        so the sanitizer sees the in-flight rpc, and the handler runs
        on the pool thread after registering via on_recv."""

        _closed = False

        def _handle(self, method, lc):
            t = rpc_mod.TRACE
            if t is not None:
                t.on_recv("gcs", "daemon", method, lc)
            started.set()
            if g._lock.acquire(timeout=8.0):
                g._lock.release()
            return {"ok": True}

        def call_async(self, method, payload=None, **kw):
            t = rpc_mod.TRACE
            lc = t.on_send("gcs", "daemon", method) if t is not None \
                else None
            fut = pool.submit(self._handle, method, lc)
            # the handler must be REGISTERED (on_recv) before the
            # caller blocks on the reply, or the probe round becomes
            # schedule-sensitive
            started.wait(5.0)
            return fut

        def notify(self, method, payload=None, **kw):
            t = rpc_mod.TRACE
            lc = None
            if t is not None:
                lc = t.on_send("gcs", "daemon", method)
                osb = getattr(t, "on_send_bytes", None)
                if osb is not None:
                    osb(method, 0, "notify")
            pool.submit(self._handle, method, lc)

    g._daemon_clients = {"n1": _Client()}
    try:
        GcsServer.rpc_stream_ack(
            g, {"task_id": "t-probe", "consumed": 1}, None)
    finally:
        pool.shutdown(wait=True)


def _probe_dag_read_under_lock(_round: int) -> None:
    """dag layer: a reader thread in the REAL per-output read-retry
    loop vs a closer thread driving the REAL ``teardown``. Clean code
    reads with no lock held — teardown proceeds, the read unblocks with
    a drained/timeout error; the seeded ``chan-read-under-lock`` branch
    parks the read while HOLDING ``_life_lock``: closer blocks on the
    lock, reader blocks on the channel whose writer end the closer
    owns — a lock-channel wait cycle."""
    import tempfile

    from ray_tpu.dag import channel as chan_mod
    from ray_tpu.dag.compiled import CompiledDAG

    dag = object.__new__(CompiledDAG)
    dag._life_lock = threading.Lock()
    dag._torn_down = False
    dag._seq = 0
    dag._inputs = []
    dag._outputs = []
    dag.dag_id = "wait-probe"
    dag._rt = type("_Rt", (), {
        "dag_teardown": staticmethod(lambda _id: None),
        "dag_state": staticmethod(lambda _id: {}),
    })()

    created = threading.Event()
    holding = threading.Event()
    path = tempfile.mktemp(prefix="wg-chan-")
    key = "wg-probe"
    errs: List[BaseException] = []

    def closer():
        # the CLOSER creates the channel so the writer end — the
        # resource the parked reader waits on — is owned by the thread
        # that will block on _life_lock
        ch = chan_mod.Channel.create(path, capacity=4096, key=key)
        created.set()
        holding.wait(8.0)
        try:
            CompiledDAG.teardown(dag)
        finally:
            ch.close()
            ch.detach()

    def reader():
        created.wait(8.0)
        r = chan_mod.Channel.open_wait(path, key, timeout=8.0)
        try:
            deadline = time.monotonic() + 2.5
            # should_stop fires INSIDE the read wait loop, i.e. after
            # the seeded branch took _life_lock: only then may the
            # closer start its teardown (Event.set returns None -> the
            # probe never actually stops the read)
            CompiledDAG._read_output(
                dag, r, deadline,
                should_stop=lambda: (holding.set() or False))
        except chan_mod.ChannelTimeoutError:
            pass
        except chan_mod.ChannelClosedError:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errs.append(e)
        finally:
            r.detach()

    t1 = threading.Thread(target=closer, name="waitprobe-closer")
    t2 = threading.Thread(target=reader, name="waitprobe-reader")
    t2.start()
    t1.start()
    t1.join(20.0)
    t2.join(20.0)
    try:
        os.unlink(path)
    except OSError:
        pass
    if errs:
        raise errs[0]


WAIT_PROBES = {
    "gcs-stream-ack-reentry": _probe_gcs_stream_ack,
    "dag-read-under-lock": _probe_dag_read_under_lock,
}


def _seed_sets(names: Sequence[str]):
    """(module SEEDED_BUGS set, prior contents) per module touched.
    Unknown names are an error: silently ignoring a typo'd seed would
    make a never-armed run read as 'seeded and clean'."""
    known = {bug for bug, _m, _p in SEEDED_WAITS}
    unknown = [n for n in names if n not in known]
    if unknown:
        raise ValueError(
            f"unknown seeded wait(s) {unknown}; have {sorted(known)}"
        )
    touched = []
    for bug, modname, _probe in SEEDED_WAITS:
        mod = importlib.import_module(modname)
        touched.append((mod.SEEDED_BUGS, set(mod.SEEDED_BUGS)))
        if bug in names:
            mod.SEEDED_BUGS.add(bug)
    return touched


def run_probe(name: str, seeded_bugs: Sequence[str] = (),
              rounds: int = 3, stall_warn_s: float = 30.0) -> ProbeResult:
    """Run one probe for up to ``rounds`` rounds (stop as soon as a
    deadlock is reported). With a seeded bug armed the sanitizer must
    detect within the gate bar lint_gate enforces (<= 2 rounds)."""
    if name not in WAIT_PROBES:
        raise ValueError(
            f"unknown wait probe {name!r}; have {sorted(WAIT_PROBES)}"
        )
    prev = _seed_sets(seeded_bugs)
    san = WaitSanitizer(stall_warn_s=stall_warn_s)
    ran = 0
    try:
        san.install()
        for i in range(rounds):
            ran = i + 1
            WAIT_PROBES[name](i)
            if san.found:
                break
    finally:
        san.uninstall()
        for bugset, before in prev:
            bugset.clear()
            bugset.update(before)
    return ProbeResult(name, tuple(seeded_bugs), san.found, ran,
                       list(san.deadlocks), list(san.stalls))
