"""Per-entity lifecycle state machines: declared protocol + AST extraction.

The control plane keeps each entity's lifecycle in a stringly-typed
status field — ``self.actors[aid]["state"] = "ALIVE"``, ``pg["state"] =
"PENDING"``, ``ent["state"] = "COMMITTED"`` — with the legal transition
structure living only in reviewers' heads. This module makes it
machine-checked:

- :data:`MACHINES` *declares* the intended state machine per entity
  (actor, placement group, dag, node, job, daemon-side 2PC bundle, task
  report statuses; the object lifecycle is declared for documentation
  but enforced dynamically by ``invariants.py``, since objects carry no
  status field);
- :func:`extract_module` AST-extracts every status-field **write**
  (including dict-literal row creations) and the locally *observed*
  states (positive ``== "S"`` / ``in ("S", ...)`` guards whose branch
  dominates the write) from ``cluster/gcs.py`` / ``cluster/
  node_daemon.py``;
- the ``illegal-state-transition`` checker (``checkers.py``) validates
  each write against the declared machine: unknown state strings
  (typos), row creations in non-initial states, writes of states no
  declared edge ever produces, and guarded writes whose observed source
  state has no edge to the written state.

Observation extraction is deliberately branch-local and positive-only
(a write under ``if x["state"] == "A":`` observes {A}; negations,
``!=``, and else-branches observe nothing), so the checker never guesses
— everything it flags is either an undeclared state or an undeclared
transition out of a state the code *explicitly matched*.

The extraction lands in the ProtocolIndex (``--dump-protocol`` emits it
under ``"statemachines"``), so the declared/extracted surfaces are
diffable and the explorer's scenarios, the invariant checker, and this
static model can be cross-checked.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.analysis.core import ModuleContext

#: ``self.<attr>`` tables whose rows carry a lifecycle field
ENTITY_TABLES: Dict[str, str] = {
    "actors": "actor",
    "placement_groups": "pg",
    "nodes": "node",
    "dags": "dag",
    "jobs": "job",
    "_bundles": "bundle",
}

#: row-parameter name heuristics for lock-held helpers that take the row
#: itself (``_maybe_restart_actor_locked(self, a, cause)``)
ENTITY_PARAMS: Dict[str, str] = {
    "a": "actor", "actor": "actor", "pg": "pg", "dag": "dag",
    "n": "node", "node": "node", "ent": "bundle",
}

#: lifecycle field per entity ("alive" is a bool field: True=ALIVE)
STATE_FIELD: Dict[str, str] = {
    "actor": "state", "pg": "state", "dag": "state", "job": "state",
    "bundle": "state", "node": "alive",
}

#: secondary lifecycle fields riding on an existing entity row:
#: (row entity, field name) -> machine entity. The node row carries two
#: machines — liveness (``alive``, bool) and gray-failure health
#: (``health``, string) — and the extractor routes each field's writes
#: to its own machine.
FIELD_MACHINES: Dict[Tuple[str, str], str] = {
    ("node", "health"): "node-health",
}

#: modules the extractor applies to (basename match)
STATE_MODULES = ("gcs.py", "node_daemon.py")


@dataclasses.dataclass(frozen=True)
class StateMachine:
    entity: str
    states: FrozenSet[str]
    initial: FrozenSet[str]
    edges: FrozenSet[Tuple[str, str]]
    #: None = statically checked; otherwise names the dynamic checker
    enforced_by: Optional[str] = None

    def targets(self) -> Set[str]:
        return {dst for _src, dst in self.edges}

    def to_dict(self) -> Dict:
        return {
            "entity": self.entity,
            "states": sorted(self.states),
            "initial": sorted(self.initial),
            "edges": sorted([list(e) for e in self.edges]),
            "enforced_by": self.enforced_by,
        }


def _m(entity, states, initial, edges, enforced_by=None) -> StateMachine:
    return StateMachine(
        entity=entity,
        states=frozenset(states),
        initial=frozenset(initial),
        edges=frozenset(edges),
        enforced_by=enforced_by,
    )


#: The declared protocol. Every edge corresponds to a handler path in
#: cluster/gcs.py / cluster/node_daemon.py; the explorer's scenarios
#: drive most of them dynamically.
MACHINES: Dict[str, StateMachine] = {
    "actor": _m(
        "actor",
        states=["PENDING", "STARTING", "ALIVE", "RESTARTING",
                "RESTARTING_GCS", "DEAD"],
        # PENDING via register_actor; ALIVE via node_sync backfill after
        # a GCS restart (the daemon re-reports a live actor)
        initial=["PENDING", "ALIVE"],
        edges=[
            ("PENDING", "STARTING"),      # creation dispatched
            ("PENDING", "RESTARTING"),    # died before dispatch, budget left
            ("PENDING", "DEAD"),          # kill / creation failed
            ("STARTING", "ALIVE"),        # creation FINISHED
            ("STARTING", "PENDING"),      # retryable creation failure
            ("STARTING", "RESTARTING"),   # node died mid-creation
            ("STARTING", "DEAD"),
            ("ALIVE", "RESTARTING"),      # worker/node death, budget left
            ("ALIVE", "RESTARTING_GCS"),  # snapshot restore
            ("ALIVE", "DEAD"),
            ("RESTARTING", "STARTING"),   # re-dispatch
            ("RESTARTING", "ALIVE"),      # node_sync found it live after all
            ("RESTARTING", "DEAD"),
            ("RESTARTING_GCS", "ALIVE"),  # daemon re-sync confirmed
            ("RESTARTING_GCS", "DEAD"),
        ],
    ),
    "pg": _m(
        "pg",
        states=["PENDING", "PREPARING", "CREATED"],
        initial=["PENDING", "PREPARING"],  # infeasible-now vs staged
        edges=[
            ("PENDING", "PREPARING"),   # staged for 2PC
            ("PREPARING", "CREATED"),   # both phases acked
            ("PREPARING", "PENDING"),   # prepare/commit failed -> re-park
            ("CREATED", "PENDING"),     # member node died -> re-pack
        ],
    ),
    "dag": _m(
        "dag",
        states=["RUNNING", "BROKEN"],
        initial=["RUNNING"],
        edges=[("RUNNING", "BROKEN")],
    ),
    "node": _m(
        "node",
        states=["ALIVE", "DEAD"],  # the boolean `alive` field
        initial=["ALIVE"],
        edges=[("ALIVE", "DEAD"), ("DEAD", "ALIVE")],
    ),
    # gray-failure defense plane (gcs._gray_sweep + quarantine helpers):
    # an ALIVE node's health rides the suspicion score through
    # OK -> SUSPECT -> QUARANTINED -> PROBATION -> OK, with instant
    # relapse from PROBATION and manual quarantine from any pre-mask
    # state (rpc_quarantine_node).
    "node-health": _m(
        "node-health",
        states=["OK", "SUSPECT", "QUARANTINED", "PROBATION"],
        initial=["OK"],
        edges=[
            ("OK", "SUSPECT"),            # score crossed quarantine_high
            ("SUSPECT", "OK"),            # decayed below quarantine_low
            ("SUSPECT", "QUARANTINED"),   # sustained over N sweeps
            ("OK", "QUARANTINED"),        # manual rpc_quarantine_node
            ("QUARANTINED", "PROBATION"), # clean probes earned exit
            ("PROBATION", "OK"),          # probation served clean
            ("PROBATION", "QUARANTINED"), # relapse: straight back
        ],
    ),
    "job": _m(
        "job",
        states=["RUNNING", "FINISHED"],
        initial=["RUNNING"],
        edges=[("RUNNING", "FINISHED")],
    ),
    "bundle": _m(
        "bundle",
        states=["PREPARED", "COMMITTED"],
        initial=["PREPARED"],
        edges=[("PREPARED", "COMMITTED"),
               ("COMMITTED", "COMMITTED")],  # idempotent re-commit
    ),
    # task lifecycle lives in report payloads, not a table row: the
    # static check is vocabulary-only (a typo'd status string silently
    # falls through every status dispatch); ordering is checked
    # dynamically (exactly-once / exec-seq invariants)
    "task-status": _m(
        "task-status",
        states=["FINISHED", "FAILED", "WORKER_DIED", "NODE_DIED",
                "DEPS_LOST", "DEPS_UNAVAILABLE", "UNSCHEDULABLE",
                "ACTOR_UNREACHABLE", "ACTOR_DEAD", "DAG_ITER"],
        initial=["FINISHED", "FAILED", "WORKER_DIED", "NODE_DIED",
                 "DEPS_LOST", "DEPS_UNAVAILABLE", "UNSCHEDULABLE",
                 "ACTOR_UNREACHABLE", "ACTOR_DEAD", "DAG_ITER"],
        edges=[],
    ),
    # declared for completeness; enforced by the object-lifecycle
    # invariant in invariants.py (objects carry no status field)
    "object": _m(
        "object",
        states=["CREATED", "LOCATED", "FREED"],
        initial=["CREATED"],
        edges=[("CREATED", "LOCATED"), ("LOCATED", "FREED"),
               ("FREED", "CREATED")],
        enforced_by="invariants.check_trace (object-lifecycle)",
    ),
}


@dataclasses.dataclass
class StateWrite:
    entity: str
    field: str
    value: str  # normalized state (bools map to ALIVE/DEAD)
    path: str
    line: int
    end_line: int
    line_text: str
    func: str
    creation: bool  # row creation (dict literal) vs field overwrite
    observed: FrozenSet[str]  # branch-local positive guards

    def to_dict(self) -> Dict:
        return {
            "entity": self.entity, "field": self.field,
            "value": self.value, "path": self.path, "line": self.line,
            "func": self.func, "creation": self.creation,
            "observed": sorted(self.observed),
        }


def applies_to(ctx: ModuleContext) -> bool:
    base = ctx.relpath.replace("\\", "/").rsplit("/", 1)[-1]
    return base in STATE_MODULES


def _norm_state(entity: str, value: ast.AST) -> Optional[str]:
    """Constant state value -> normalized name, None if non-constant."""
    if not isinstance(value, ast.Constant):
        return None
    v = value.value
    if entity == "node":
        if v is True:
            return "ALIVE"
        if v is False:
            return "DEAD"
        return None
    return v if isinstance(v, str) else None


class _FuncExtractor(ast.NodeVisitor):
    """Walks one function: resolves row variables to entities, collects
    state writes with their branch-local positive observations."""

    def __init__(self, ctx: ModuleContext, func: ast.AST, qualname: str):
        self.ctx = ctx
        self.func = func
        self.qualname = qualname
        self.out: List[StateWrite] = []
        # var name -> entity, resolved from `x = self.<table>...`
        # assignments, `for x in self.<table>.values()`, and row-param
        # name heuristics
        self.var_entity: Dict[str, str] = {}
        args = getattr(func, "args", None)
        if args is not None:
            for a in args.args:
                if a.arg in ENTITY_PARAMS:
                    self.var_entity[a.arg] = ENTITY_PARAMS[a.arg]
        self._observed: List[Tuple[str, FrozenSet[str]]] = []  # stack

    # ------------------------------------------------- entity resolution

    def _table_entity(self, node: ast.AST) -> Optional[str]:
        """`self.<table>` (possibly behind .get/.pop/[k]/.values()) ->
        entity."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return ENTITY_TABLES.get(node.attr)
        return None

    def _row_entity(self, node: ast.AST) -> Optional[str]:
        """Entity of an expression that denotes one table ROW."""
        # self.table[k]
        if isinstance(node, ast.Subscript):
            return self._table_entity(node.value)
        # self.table.get(k) / self.table.pop(k)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("get", "pop"):
                return self._table_entity(node.func.value)
        if isinstance(node, ast.Name):
            return self.var_entity.get(node.id)
        return None

    def _learn_assign(self, node: ast.Assign) -> None:
        ent = self._row_entity(node.value)
        if ent is None:
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.var_entity[t.id] = ent

    def _learn_for(self, node: ast.For) -> None:
        # for x in self.table.values(): / for k, x in self.table.items():
        it = node.iter
        ent = None
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("values", "items"):
            ent = self._table_entity(it.func.value)
            # list(self.table.items()) wrapper
            if ent is None and isinstance(it.func.value, ast.Call):
                inner = it.func.value
                if isinstance(inner.func, ast.Name) and \
                        inner.func.id == "list":
                    pass
        elif isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "list" and it.args:
            inner = it.args[0]
            if isinstance(inner, ast.Call) and \
                    isinstance(inner.func, ast.Attribute) and \
                    inner.func.attr in ("values", "items"):
                ent = self._table_entity(inner.func.value)
                it = inner
        if ent is None:
            return
        is_items = isinstance(it, ast.Call) and \
            isinstance(it.func, ast.Attribute) and it.func.attr == "items"
        tgt = node.target
        if is_items and isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 \
                and isinstance(tgt.elts[1], ast.Name):
            self.var_entity[tgt.elts[1].id] = ent
        elif not is_items and isinstance(tgt, ast.Name):
            self.var_entity[tgt.id] = ent

    # ---------------------------------------------------- observations

    def _guard_states(self, test: ast.AST) -> List[Tuple[str, FrozenSet[str]]]:
        """Positive state observations in an if-test: [(entity, states)].
        `x["state"] == "S"`, `x.get("state") == "S"`, `... in ("A","B")`,
        and conjunctions thereof. Negations contribute nothing."""
        out: List[Tuple[str, FrozenSet[str]]] = []
        tests = [test]
        while tests:
            t = tests.pop()
            if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And):
                tests.extend(t.values)
                continue
            if not isinstance(t, ast.Compare) or len(t.ops) != 1:
                continue
            op = t.ops[0]
            ent_field = self._state_read(t.left)
            if ent_field is None:
                continue
            entity, field = ent_field
            if field != STATE_FIELD.get(entity):
                entity = FIELD_MACHINES.get((entity, field))
                if entity is None:
                    continue
            comp = t.comparators[0]
            states: Set[str] = set()
            if isinstance(op, ast.Eq):
                s = _norm_state(entity, comp)
                if s is not None:
                    states.add(s)
            elif isinstance(op, ast.In) and isinstance(
                comp, (ast.Tuple, ast.List, ast.Set)
            ):
                for e in comp.elts:
                    s = _norm_state(entity, e)
                    if s is not None:
                        states.add(s)
            if states:
                out.append((entity, frozenset(states)))
        return out

    def _state_read(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """`x["state"]` / `x.get("state")` -> (entity, field)."""
        if isinstance(node, ast.Subscript):
            ent = self._row_entity(node.value)
            if ent is not None and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                return ent, node.slice.value
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args:
            ent = self._row_entity(node.func.value)
            k = node.args[0]
            if ent is not None and isinstance(k, ast.Constant) \
                    and isinstance(k.value, str):
                return ent, k.value
        return None

    def _observed_for(self, entity: str) -> FrozenSet[str]:
        obs: Set[str] = set()
        for ent, states in self._observed:
            if ent == entity:
                obs |= states
        return frozenset(obs)

    # ---------------------------------------------------------- visits

    def _emit(self, node: ast.AST, entity: str, field: str, value: str,
              creation: bool) -> None:
        self.out.append(StateWrite(
            entity=entity, field=field, value=value,
            path=self.ctx.relpath, line=node.lineno,
            end_line=getattr(node, "end_lineno", None) or node.lineno,
            line_text=self.ctx.line_text(node.lineno),
            func=self.qualname, creation=creation,
            observed=frozenset() if creation else self._observed_for(entity),
        ))

    def _scan_creation_dict(self, node: ast.AST, entity: str,
                            d: ast.Dict) -> None:
        field = STATE_FIELD.get(entity)
        for k, v in zip(d.keys, d.values):
            if not isinstance(k, ast.Constant):
                continue
            if k.value == field:
                ment = entity
            else:
                ment = FIELD_MACHINES.get((entity, k.value))
                if ment is None:
                    continue
            s = _norm_state(ment, v)
            if s is not None or isinstance(v, ast.Constant):
                self._emit(node, ment, k.value,
                           s if s is not None else repr(v.value),
                           creation=True)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._learn_assign(node)
        for t in node.targets:
            # x["state"] = <const> / self.table[k]["state"] = <const>;
            # conditional writes (`"A" if cond else "B"`) emit one write
            # per constant arm
            if isinstance(t, ast.Subscript) and isinstance(
                t.slice, ast.Constant
            ) and isinstance(t.slice.value, str):
                ent = self._row_entity(t.value)
                if ent is not None and t.slice.value != STATE_FIELD.get(ent):
                    ent = FIELD_MACHINES.get((ent, t.slice.value))
                if ent is not None:
                    values = (
                        [node.value.body, node.value.orelse]
                        if isinstance(node.value, ast.IfExp)
                        else [node.value]
                    )
                    for v in values:
                        s = _norm_state(ent, v)
                        if s is not None or isinstance(v, ast.Constant):
                            self._emit(
                                node, ent, t.slice.value,
                                s if s is not None else repr(v.value),
                                creation=False,
                            )
            # self.table[k] = {... "state": X ...} (row creation)
            if isinstance(t, ast.Subscript):
                ent = self._table_entity(t.value)
                if ent is not None and isinstance(node.value, ast.Dict):
                    self._scan_creation_dict(node, ent, node.value)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._learn_for(node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        guards = self._guard_states(node.test)
        self._observed.extend(guards)
        for child in node.body:
            self.visit(child)
        del self._observed[len(self._observed) - len(guards):]
        for child in node.orelse:
            self.visit(child)

    def visit_FunctionDef(self, node):
        pass  # nested defs get their own extractor pass

    visit_AsyncFunctionDef = visit_FunctionDef


def extract_module(ctx: ModuleContext) -> List[StateWrite]:
    """Every status-field write (+ task-status literals) in a
    gcs/node_daemon module."""
    if not applies_to(ctx):
        return []
    out: List[StateWrite] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fx = _FuncExtractor(ctx, node, node.name)
        for stmt in node.body:
            fx.visit(stmt)
        out.extend(fx.out)
        # task-status vocabulary: literal {"status": "X"} payload keys
        # and `status == "X"` / `status in (...)` dispatches
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for k, v in zip(sub.keys, sub.values):
                    if (
                        isinstance(k, ast.Constant) and k.value == "status"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        out.append(StateWrite(
                            entity="task-status", field="status",
                            value=v.value, path=ctx.relpath,
                            line=sub.lineno,
                            end_line=getattr(sub, "end_lineno", None)
                            or sub.lineno,
                            line_text=ctx.line_text(sub.lineno),
                            func=node.name, creation=True,
                            observed=frozenset(),
                        ))
    out.sort(key=lambda w: (w.path, w.line))
    return out


def check_writes(writes: List[StateWrite]) -> List[Tuple[StateWrite, str]]:
    """Validate extracted writes against the declared machines. Returns
    [(write, problem)] — empty on a protocol-conforming tree."""
    problems: List[Tuple[StateWrite, str]] = []
    for w in writes:
        m = MACHINES.get(w.entity)
        if m is None or m.enforced_by is not None:
            continue
        if w.value not in m.states:
            problems.append((w, (
                f"{w.entity} state {w.value!r} is not a declared state "
                f"(have {sorted(m.states)}) — typo or undeclared "
                "lifecycle extension"
            )))
            continue
        if w.entity == "task-status":
            continue  # vocabulary-only
        if w.creation:
            if w.value not in m.initial:
                problems.append((w, (
                    f"{w.entity} row created in state {w.value!r}; "
                    f"declared initial states: {sorted(m.initial)}"
                )))
            continue
        if w.observed:
            bad = [s for s in w.observed if (s, w.value) not in m.edges]
            if bad:
                problems.append((w, (
                    f"{w.entity} transition {sorted(bad)} -> {w.value!r} "
                    "has no declared edge (the guard observes a state "
                    "this write is illegal from)"
                )))
        elif w.value not in m.targets():
            problems.append((w, (
                f"{w.entity} state {w.value!r} is never the target of a "
                "declared edge — no handler may write it outside row "
                "creation"
            )))
    return problems
