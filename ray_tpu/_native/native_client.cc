// Native (C++) client for ray_tpu — the C++ worker-API equivalent.
//
// Reference: cpp/src/ray/ (the C++ worker frontend) and the cross-language
// call path (Java/C++ workers invoking Python functions by module path).
// The TPU-idiomatic split keeps Python as the only task *execution*
// language (tasks are jitted JAX programs; a native executor would buy
// nothing on the compute path), so the native frontend is a thin,
// dependency-free client for the head's HTTP/JSON gateway
// (ray_tpu/dashboard/head.py):
//
//   rt_call(host, port, body_json)   -> POST /api/call   (run module:attr)
//   rt_submit_job(host, port, body)  -> POST /api/jobs   (entrypoint cmd)
//   rt_get(host, port, path)         -> GET  any state route
//
// All functions return a malloc'd NUL-terminated response body (JSON);
// the caller frees it with rt_free. NULL on connect/IO failure. Blocking,
// one TCP connection per call (the gateway is synchronous anyway).
//
// Build: compiled on first use by ray_tpu._native.load_library (g++
// -shared); usable from any C/C++ program by linking the same .so.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

int connect_to(const char* host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  std::snprintf(portbuf, sizeof(portbuf), "%d", port);
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr) {
    return -1;
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

bool send_all(int fd, const char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = send(fd, buf + off, n - off, 0);
    if (k <= 0) return false;
    off += static_cast<size_t>(k);
  }
  return true;
}

// Reads the whole HTTP/1.1 response (Content-Length framing; the head
// always sets it) and returns a malloc'd copy of the body.
char* read_response(int fd) {
  std::string data;
  char buf[8192];
  size_t header_end = std::string::npos;
  long content_len = -1;
  for (;;) {
    ssize_t k = recv(fd, buf, sizeof(buf), 0);
    if (k < 0) return nullptr;
    if (k == 0) break;
    data.append(buf, static_cast<size_t>(k));
    if (header_end == std::string::npos) {
      header_end = data.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        // parse Content-Length (case-insensitive)
        std::string lower;
        lower.reserve(header_end);
        for (size_t i = 0; i < header_end; i++)
          lower.push_back(static_cast<char>(tolower(data[i])));
        size_t p = lower.find("content-length:");
        if (p != std::string::npos) {
          content_len = std::strtol(data.c_str() + p + 15, nullptr, 10);
        }
      }
    }
    if (header_end != std::string::npos && content_len >= 0 &&
        data.size() >= header_end + 4 + static_cast<size_t>(content_len)) {
      break;
    }
  }
  if (header_end == std::string::npos) return nullptr;
  std::string body = data.substr(header_end + 4);
  if (content_len >= 0 && body.size() > static_cast<size_t>(content_len)) {
    body.resize(static_cast<size_t>(content_len));
  }
  char* out = static_cast<char*>(std::malloc(body.size() + 1));
  if (out == nullptr) return nullptr;
  std::memcpy(out, body.data(), body.size());
  out[body.size()] = '\0';
  return out;
}

char* request(const char* host, int port, const char* method,
              const char* path, const char* body) {
  int fd = connect_to(host, port);
  if (fd < 0) return nullptr;
  size_t blen = body ? std::strlen(body) : 0;
  std::string req;
  req.reserve(256 + blen);
  req += method;
  req += " ";
  req += path;
  req += " HTTP/1.1\r\nHost: ";
  req += host;
  req += "\r\nConnection: close\r\nContent-Type: application/json\r\n";
  char lenbuf[64];
  std::snprintf(lenbuf, sizeof(lenbuf), "Content-Length: %zu\r\n\r\n", blen);
  req += lenbuf;
  if (blen) req.append(body, blen);
  char* out = nullptr;
  if (send_all(fd, req.data(), req.size())) out = read_response(fd);
  close(fd);
  return out;
}

}  // namespace

extern "C" {

// GET any route, e.g. "/api/nodes", "/api/jobs/job-0001".
char* rt_get(const char* host, int port, const char* path) {
  return request(host, port, "GET", path, nullptr);
}

// POST a JSON body to any route.
char* rt_post(const char* host, int port, const char* path,
              const char* json_body) {
  return request(host, port, "POST", path, json_body);
}

// Run a Python callable as a cluster task and return the gateway's JSON
// response ({"result": ...} or {"error": ...}).
// json_body: {"func": "module:attr", "args": [...], "kwargs": {...}}
char* rt_call(const char* host, int port, const char* json_body) {
  return request(host, port, "POST", "/api/call", json_body);
}

// Submit a job entrypoint: {"entrypoint": "python my_driver.py"}.
char* rt_submit_job(const char* host, int port, const char* json_body) {
  return request(host, port, "POST", "/api/jobs", json_body);
}

void rt_free(char* p) { std::free(p); }

}  // extern "C"
