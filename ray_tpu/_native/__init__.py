"""Native (C++) components of ray_tpu.

The reference implements its data plane and runtime in C++
(src/ray/object_manager/plasma/, src/ray/raylet/); ray_tpu keeps the same
split: compute on TPU via JAX/XLA, the host data plane in C++.  Sources are
compiled on first use with the system toolchain (no pip deps) and cached
next to the source, keyed by source mtime.
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()


def _build(src: str, out: str) -> None:
    # per-process tmp name: concurrent first-use builds from the daemon and
    # its subprocess workers must not interleave writes before the atomic
    # publish below
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, src, "-lpthread", "-lrt",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)


def load_library(name: str):
    """Compile (if stale) and dlopen `<name>.cc` from this directory."""
    import ctypes

    src = os.path.join(_HERE, name + ".cc")
    out = os.path.join(_HERE, "lib" + name + ".so")
    with _LOCK:
        if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
            _build(src, out)
    return ctypes.CDLL(out)
