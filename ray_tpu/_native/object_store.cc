// ray_tpu shared-memory object store — the plasma equivalent.
//
// Reference behavior being matched (not translated):
//   src/ray/object_manager/plasma/store.cc          (create/seal/get/release)
//   src/ray/object_manager/plasma/object_lifecycle_manager.cc
//   src/ray/object_manager/plasma/eviction_policy.cc (LRU)
//   src/ray/object_manager/plasma/client.cc          (worker-side mmap client)
//
// Design: ONE POSIX shm segment per node holds a header, a fixed open-address
// hash table of object entries, and a data arena managed by a boundary-tag
// free list.  Every process (daemon + workers) maps the same segment, so a
// "get" is just (base + offset) — zero-copy, exactly plasma's trick, without
// the unix-socket handshake: coordination is a process-shared robust mutex
// living inside the segment itself.
//
// All offsets are relative to the start of the data arena so mappings at
// different virtual addresses agree.

#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <fcntl.h>
#include <unistd.h>

#include <errno.h>
#include <deque>
#include <mutex>
#include <new>

namespace {

constexpr uint64_t kMagic = 0x5241595f545055ULL;  // "RAY_TPU"
constexpr uint64_t kNil = ~0ULL;
constexpr uint64_t kAlign = 64;
constexpr int kIdLen = 20;

enum State : uint8_t {
  kFree = 0,      // slot never used (stops probe)
  kCreated = 1,   // allocated, being written, not readable, not evictable
  kSealed = 2,    // immutable, readable, evictable when unpinned
  kTombstone = 3, // deleted slot (probe continues)
};

struct Entry {
  uint8_t id[kIdLen];
  uint8_t state;
  uint8_t pending_delete;
  uint8_t pad_[2];
  uint32_t refcount;
  uint64_t offset;  // data offset (arena-relative) of the payload
  uint64_t size;
  uint64_t lru_tick;
};

// Free block: header lives at the block's arena offset.
struct FreeBlock {
  uint64_t size;  // total block size including the 8-byte alloc header
  uint64_t next;  // arena offset of next free block, or kNil
};

// Allocated block: 8-byte header holding total block size, then payload.
struct AllocHeader {
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;   // arena bytes
  uint64_t used;       // bytes currently allocated (incl. headers)
  uint32_t max_objects;
  uint32_t n_objects;
  uint64_t lru_counter;
  uint64_t free_head;  // arena offset of first free block, or kNil
  uint64_t n_evictions;
  uint64_t bytes_evicted;
  pthread_mutex_t mutex;
};

struct Mapping {
  void* addr = nullptr;
  size_t len = 0;
  Header* hdr = nullptr;
  Entry* entries = nullptr;
  uint8_t* arena = nullptr;
  bool valid = false;
};

// deque: elements never move on push_back, so Mapping* stays valid while
// another thread attaches; the mutex guards push_back vs. size reads
// (ctypes releases the GIL during calls, so rts_* can run concurrently)
std::deque<Mapping>& mappings() {
  static std::deque<Mapping> m;
  return m;
}

std::mutex& mappings_mutex() {
  static std::mutex mu;
  return mu;
}

uint64_t align_up(uint64_t x, uint64_t a) { return (x + a - 1) & ~(a - 1); }

uint64_t entries_offset() { return align_up(sizeof(Header), kAlign); }

uint64_t arena_offset(uint32_t max_objects) {
  return align_up(entries_offset() + sizeof(Entry) * (uint64_t)max_objects, kAlign);
}

// A lock guard that heals robust mutexes left locked by a dead worker.
struct Lock {
  pthread_mutex_t* m;
  explicit Lock(pthread_mutex_t* mu) : m(mu) {
    int rc = pthread_mutex_lock(m);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(m);
  }
  ~Lock() { pthread_mutex_unlock(m); }
};

uint64_t hash_id(const uint8_t* id) {
  uint64_t h;
  memcpy(&h, id, 8);
  // ids are content-random (sha/random), so the raw prefix is already a hash;
  // mix anyway so adversarial low-entropy ids don't cluster.
  h ^= h >> 33; h *= 0xff51afd7ed558ccdULL; h ^= h >> 33;
  return h;
}

// Find the slot holding `id`, or -1.
int64_t find_slot(Mapping& m, const uint8_t* id) {
  uint32_t n = m.hdr->max_objects;
  uint64_t i = hash_id(id) % n;
  for (uint32_t probes = 0; probes < n; ++probes) {
    Entry& e = m.entries[i];
    if (e.state == kFree) return -1;
    if (e.state != kTombstone && memcmp(e.id, id, kIdLen) == 0) return (int64_t)i;
    i = (i + 1) % n;
  }
  return -1;
}

// Find a slot to insert `id` into (first tombstone or free), or -1 if full.
int64_t insert_slot(Mapping& m, const uint8_t* id) {
  uint32_t n = m.hdr->max_objects;
  uint64_t i = hash_id(id) % n;
  int64_t first_tomb = -1;
  for (uint32_t probes = 0; probes < n; ++probes) {
    Entry& e = m.entries[i];
    if (e.state == kFree) return first_tomb >= 0 ? first_tomb : (int64_t)i;
    if (e.state == kTombstone && first_tomb < 0) first_tomb = (int64_t)i;
    i = (i + 1) % n;
  }
  return first_tomb;
}

// First-fit allocation from the free list.  Returns arena offset of the
// payload (past the AllocHeader), or kNil.
uint64_t arena_alloc(Mapping& m, uint64_t payload) {
  uint64_t need = align_up(payload + sizeof(AllocHeader), kAlign);
  uint64_t prev = kNil;
  uint64_t cur = m.hdr->free_head;
  while (cur != kNil) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(m.arena + cur);
    if (fb->size >= need) {
      uint64_t remain = fb->size - need;
      uint64_t next = fb->next;
      if (remain >= kAlign * 2) {
        // split: tail remains free
        uint64_t tail_off = cur + need;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(m.arena + tail_off);
        tail->size = remain;
        tail->next = next;
        next = tail_off;
      } else {
        need = fb->size;  // absorb the sliver
      }
      if (prev == kNil) m.hdr->free_head = next;
      else reinterpret_cast<FreeBlock*>(m.arena + prev)->next = next;
      AllocHeader* ah = reinterpret_cast<AllocHeader*>(m.arena + cur);
      ah->size = need;
      m.hdr->used += need;
      return cur + sizeof(AllocHeader);
    }
    prev = cur;
    cur = fb->next;
  }
  return kNil;
}

// Free the block whose payload starts at `payload_off`, coalescing with
// adjacent free blocks (the free list is kept address-ordered to make
// coalescing a local operation).
void arena_free(Mapping& m, uint64_t payload_off) {
  uint64_t block = payload_off - sizeof(AllocHeader);
  uint64_t size = reinterpret_cast<AllocHeader*>(m.arena + block)->size;
  m.hdr->used -= size;

  uint64_t prev = kNil, cur = m.hdr->free_head;
  while (cur != kNil && cur < block) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(m.arena + cur)->next;
  }
  // link in
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(m.arena + block);
  nb->size = size;
  nb->next = cur;
  if (prev == kNil) m.hdr->free_head = block;
  else reinterpret_cast<FreeBlock*>(m.arena + prev)->next = block;
  // coalesce with next
  if (cur != kNil && block + nb->size == cur) {
    FreeBlock* cn = reinterpret_cast<FreeBlock*>(m.arena + cur);
    nb->size += cn->size;
    nb->next = cn->next;
  }
  // coalesce with prev
  if (prev != kNil) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(m.arena + prev);
    if (prev + pb->size == block) {
      pb->size += nb->size;
      pb->next = nb->next;
    }
  }
}

void free_entry(Mapping& m, Entry& e) {
  arena_free(m, e.offset);
  e.state = kTombstone;
  e.refcount = 0;
  e.pending_delete = 0;
  m.hdr->n_objects -= 1;
}

// Evict least-recently-used sealed, unpinned objects until `need` bytes could
// plausibly be satisfied (or nothing evictable remains).  Returns bytes freed.
uint64_t evict_lru(Mapping& m, uint64_t need) {
  uint64_t freed = 0;
  while (freed < need) {
    int64_t victim = -1;
    uint64_t best = ~0ULL;
    for (uint32_t i = 0; i < m.hdr->max_objects; ++i) {
      Entry& e = m.entries[i];
      if (e.state == kSealed && e.refcount == 0 && e.lru_tick < best) {
        best = e.lru_tick;
        victim = (int64_t)i;
      }
    }
    if (victim < 0) break;
    Entry& e = m.entries[victim];
    uint64_t sz = align_up(e.size + sizeof(AllocHeader), kAlign);
    freed += sz;
    m.hdr->n_evictions += 1;
    m.hdr->bytes_evicted += e.size;
    free_entry(m, e);
  }
  return freed;
}

int64_t do_map(const char* name, bool create, uint64_t capacity, uint32_t max_objects) {
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return -(int64_t)errno;

  uint64_t total = 0;
  if (create) {
    total = arena_offset(max_objects) + align_up(capacity, kAlign);
    if (ftruncate(fd, (off_t)total) != 0) {
      int e = errno;
      close(fd);
      shm_unlink(name);
      return -(int64_t)e;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0) { int e = errno; close(fd); return -(int64_t)e; }
    total = (uint64_t)st.st_size;
  }

  void* addr = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (addr == MAP_FAILED) return -(int64_t)errno;

  Mapping m;
  m.addr = addr;
  m.len = total;
  m.hdr = reinterpret_cast<Header*>(addr);

  if (create) {
    Header* h = new (addr) Header();
    h->magic = kMagic;
    h->capacity = align_up(capacity, kAlign);
    h->used = 0;
    h->max_objects = max_objects;
    h->n_objects = 0;
    h->lru_counter = 0;
    h->n_evictions = 0;
    h->bytes_evicted = 0;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    memset(reinterpret_cast<uint8_t*>(addr) + entries_offset(), 0,
           sizeof(Entry) * (uint64_t)max_objects);
    m.entries = reinterpret_cast<Entry*>(reinterpret_cast<uint8_t*>(addr) + entries_offset());
    m.arena = reinterpret_cast<uint8_t*>(addr) + arena_offset(max_objects);
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(m.arena);
    fb->size = h->capacity;
    fb->next = kNil;
    h->free_head = 0;
  } else {
    if (m.hdr->magic != kMagic) {
      munmap(addr, total);
      return -1000;  // not a ray_tpu store
    }
    m.entries = reinterpret_cast<Entry*>(reinterpret_cast<uint8_t*>(addr) + entries_offset());
    m.arena = reinterpret_cast<uint8_t*>(addr) + arena_offset(m.hdr->max_objects);
  }

  m.valid = true;
  std::lock_guard<std::mutex> g(mappings_mutex());
  mappings().push_back(m);
  return (int64_t)mappings().size() - 1;
}

Mapping* get_mapping(int64_t h) {
  // hold the lock across operator[] too: push_back may rewrite the deque's
  // internal block map even though elements themselves never move; the
  // returned Mapping* stays valid after unlock
  auto& ms = mappings();
  std::lock_guard<std::mutex> g(mappings_mutex());
  if (h < 0 || (size_t)h >= ms.size() || !ms[h].valid) return nullptr;
  return &ms[h];
}

}  // namespace

extern "C" {

// All functions return >=0 on success; negative values are errors:
//   -1 generic / not found, -2 out of memory (after eviction),
//   -3 object not sealed / wrong state, -4 already exists, -errno from OS.

int64_t rts_create(const char* name, uint64_t capacity, uint32_t max_objects) {
  return do_map(name, /*create=*/true, capacity, max_objects);
}

int64_t rts_attach(const char* name) { return do_map(name, false, 0, 0); }

int rts_detach(int64_t h) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  munmap(m->addr, m->len);
  m->valid = false;
  return 0;
}

int rts_unlink(const char* name) { return shm_unlink(name) == 0 ? 0 : -errno; }

// Base address of this process's mapping of the data arena (for zero-copy
// pointer math in the client: payload pointer = rts_base(h) + offset).
uint8_t* rts_base(int64_t h) {
  Mapping* m = get_mapping(h);
  return m ? m->arena : nullptr;
}

// allow_evict=0 returns -2 instead of silently dropping LRU objects, so an
// owner that layers disk spilling on top (reference: local_object_manager.cc)
// gets to persist victims before the space is reused.
int64_t rts_obj_create2(int64_t h, const uint8_t* id, uint64_t size,
                        int allow_evict) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  if (find_slot(*m, id) >= 0) return -4;
  int64_t slot = insert_slot(*m, id);
  if (slot < 0) return -2;  // table full
  uint64_t off = arena_alloc(*m, size);
  // evict_lru counts freed bytes that may be non-contiguous; keep evicting
  // until the allocation fits or nothing evictable remains
  while (off == kNil) {
    if (!allow_evict) return -2;  // no entry written yet: clean abort
    if (evict_lru(*m, align_up(size + sizeof(AllocHeader), kAlign)) == 0)
      return -2;
    off = arena_alloc(*m, size);
  }
  Entry& e = m->entries[slot];
  memcpy(e.id, id, kIdLen);
  e.state = kCreated;
  e.pending_delete = 0;
  e.refcount = 0;
  e.offset = off;
  e.size = size;
  e.lru_tick = ++m->hdr->lru_counter;
  m->hdr->n_objects += 1;
  return (int64_t)off;
}

int64_t rts_obj_create(int64_t h, const uint8_t* id, uint64_t size) {
  return rts_obj_create2(h, id, size, /*allow_evict=*/1);
}

int rts_obj_seal(int64_t h, const uint8_t* id) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  int64_t slot = find_slot(*m, id);
  if (slot < 0) return -1;
  Entry& e = m->entries[slot];
  if (e.state != kCreated) return -3;
  e.state = kSealed;
  e.lru_tick = ++m->hdr->lru_counter;
  return 0;
}

// Pins the object.  On success writes size and returns the arena offset.
int64_t rts_obj_get(int64_t h, const uint8_t* id, uint64_t* size_out) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  int64_t slot = find_slot(*m, id);
  if (slot < 0) return -1;
  Entry& e = m->entries[slot];
  if (e.state != kSealed) return -3;
  e.refcount += 1;
  e.lru_tick = ++m->hdr->lru_counter;
  if (size_out) *size_out = e.size;
  return (int64_t)e.offset;
}

int rts_obj_release(int64_t h, const uint8_t* id) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  int64_t slot = find_slot(*m, id);
  if (slot < 0) return -1;
  Entry& e = m->entries[slot];
  if (e.refcount > 0) e.refcount -= 1;
  if (e.pending_delete && e.refcount == 0) free_entry(*m, e);
  return 0;
}

int rts_obj_delete(int64_t h, const uint8_t* id) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  int64_t slot = find_slot(*m, id);
  if (slot < 0) return -1;
  Entry& e = m->entries[slot];
  if (e.refcount > 0) {
    e.pending_delete = 1;  // freed on last release
    return 1;
  }
  free_entry(*m, e);
  return 0;
}

int rts_obj_contains(int64_t h, const uint8_t* id) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  int64_t slot = find_slot(*m, id);
  if (slot < 0) return 0;
  return m->entries[slot].state == kSealed ? 2 : 1;
}

uint64_t rts_evict(int64_t h, uint64_t nbytes) {
  Mapping* m = get_mapping(h);
  if (!m) return 0;
  Lock lock(&m->hdr->mutex);
  return evict_lru(*m, nbytes);
}

int rts_stats(int64_t h, uint64_t* used, uint64_t* capacity, uint32_t* n_objects,
              uint64_t* n_evictions, uint64_t* bytes_evicted) {
  Mapping* m = get_mapping(h);
  if (!m) return -1;
  Lock lock(&m->hdr->mutex);
  if (used) *used = m->hdr->used;
  if (capacity) *capacity = m->hdr->capacity;
  if (n_objects) *n_objects = m->hdr->n_objects;
  if (n_evictions) *n_evictions = m->hdr->n_evictions;
  if (bytes_evicted) *bytes_evicted = m->hdr->bytes_evicted;
  return 0;
}

// List sealed, unpinned object ids (for the spill scan).  Writes up to
// max_ids ids (20 bytes each) into out; returns count written.
uint32_t rts_list_evictable(int64_t h, uint8_t* out, uint32_t max_ids) {
  Mapping* m = get_mapping(h);
  if (!m) return 0;
  Lock lock(&m->hdr->mutex);
  uint32_t n = 0;
  for (uint32_t i = 0; i < m->hdr->max_objects && n < max_ids; ++i) {
    Entry& e = m->entries[i];
    if (e.state == kSealed && e.refcount == 0) {
      memcpy(out + (uint64_t)n * kIdLen, e.id, kIdLen);
      ++n;
    }
  }
  return n;
}

}  // extern "C"
