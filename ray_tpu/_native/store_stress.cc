// Thread stress harness for the shm object store, built with -fsanitize=thread
// by the test suite (reference: the bazel --config=tsan builds that gate
// src/ray/object_manager/plasma/ in upstream CI, SURVEY §5).
//
// Spawns N threads against one segment doing create/seal/get/release/delete
// with eviction pressure (arena sized to ~1/4 of the working set), then
// verifies every surviving object's payload bytes.

#include "object_store.cc"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

namespace {

void fill_id(uint8_t* id, uint64_t thread_id, uint64_t i) {
  memset(id, 0, kIdLen);
  memcpy(id, &thread_id, sizeof(thread_id));
  memcpy(id + 8, &i, sizeof(i));
}

std::atomic<uint64_t> g_errors{0};

void worker(int64_t h, uint64_t thread_id, int iters) {
  uint8_t id[kIdLen];
  for (int i = 0; i < iters; ++i) {
    uint64_t key = (uint64_t)(i % 64);
    fill_id(id, thread_id, key);
    uint64_t size = 256 + (i % 7) * 1024;
    int64_t off = rts_obj_create2(h, id, size, /*allow_evict=*/1);
    if (off >= 0) {
      uint8_t* p = rts_base(h) + off;
      memset(p, (int)(key & 0xff), size);
      if (rts_obj_seal(h, id) < 0) g_errors.fetch_add(1);
    } else if (off != -4 && off != -2) {
      g_errors.fetch_add(1);
    }
    // read-verify a random earlier object from ANY thread
    fill_id(id, (thread_id + i) % 4, (uint64_t)((i * 13) % 64));
    uint64_t got_size = 0;
    int64_t goff = rts_obj_get(h, id, &got_size);
    if (goff >= 0) {
      uint8_t* p = rts_base(h) + goff;
      uint8_t expect = (uint8_t)(((i * 13) % 64) & 0xff);
      for (uint64_t j = 0; j < got_size; j += 997) {
        if (p[j] != expect) {
          g_errors.fetch_add(1);
          break;
        }
      }
      rts_obj_release(h, id);
    }
    if (i % 17 == 0) {
      fill_id(id, thread_id, (uint64_t)(i % 64));
      rts_obj_delete(h, id);
    }
    if (i % 31 == 0) rts_evict(h, 8192);
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* name = argc > 1 ? argv[1] : "/rts_stress";
  int n_threads = argc > 2 ? atoi(argv[2]) : 4;
  int iters = argc > 3 ? atoi(argv[3]) : 20000;
  shm_unlink(name);
  int64_t h = rts_create(name, 1 << 20, 1024);
  if (h < 0) {
    fprintf(stderr, "create failed: %lld\n", (long long)h);
    return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t)
    threads.emplace_back(worker, h, (uint64_t)t, iters);
  for (auto& th : threads) th.join();
  shm_unlink(name);
  if (g_errors.load() != 0) {
    fprintf(stderr, "errors: %llu\n", (unsigned long long)g_errors.load());
    return 1;
  }
  printf("ok\n");
  return 0;
}
