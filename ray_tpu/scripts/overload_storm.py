"""Overload storm: bursty open-loop traffic past cluster saturation, A/B
over the overload control plane (ISSUE-13 acceptance; recorded as
BENCH_overload_r01.json).

    python -m ray_tpu.scripts.overload_storm [--seed N] [--duration S]
        [--mult-lo X] [--mult-hi X] [--smoke] [--json FILE]

Three phases on identical topologies (3 churn nodes x 2 CPU):

1. **peak** — open-loop at ~0.9x nominal capacity, no chaos, overload
   control ON: the single-rate throughput ceiling everything else is
   measured against.
2. **overload ON** — seeded bursty open-loop traffic at ``mult-lo``..
   ``mult-hi`` x capacity (per-100ms-tick multipliers) under chaos node
   kills, with the full control plane armed: GCS admission bound per
   driver + typed retryable rejections, client pacing + paced retries,
   and the advisory overload throttle push. The run is protocol-traced;
   the invariant checker replays it with the admission-conservation
   check in strict-terminal mode — every admitted task must terminally
   resolve.
3. **overload OFF** — the SAME seeded traffic and chaos on a fresh
   cluster with the control plane disabled: excess work piles into the
   GCS queues without bound and completion latency blows through the
   SLO — the collapse arm.

Goodput = tasks whose end-to-end latency (task-stamped completion time
minus submit time, collector-lag independent) is within the SLO, per
second of the submission window. Every submitted task is driven to a
TERMINAL outcome in the ON arm (value, typed ClusterOverloadedError, or
task error); ``silently_unresolved`` must be 0.

Gates (``--smoke`` relaxes the bars, same zero-silent-drop teeth):
goodput_ON >= ratio_bar x goodput_OFF (3x full / 2x smoke),
goodput_ON >= frac_bar x peak (0.6 full / 0.5 smoke), 0 silent drops,
0 invariant violations. Exit code: 0 = green, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import queue
import random
import sys
import threading
import time
from typing import Dict, List

# control-plane knobs for the ON arm: a tight per-driver admission bound
# (the cluster is tiny), fast retries, and low overload thresholds so the
# advisory throttle actually exercises
CONTROL_ON = {
    "admission_max_pending_per_driver": 48,
    "admission_retry_after_s": 0.1,
    "admission_pacing_enabled": True,
    "admission_pacing_max_s": 45.0,
    "overload_pending_high_per_cpu": 4.0,
    "overload_pending_low_per_cpu": 1.0,
    "log_to_driver": False,
}
# the A/B arm: admission off, pacing off, throttle thresholds unreachable
CONTROL_OFF = {
    "admission_max_pending_per_driver": 0,
    "admission_pacing_enabled": False,
    "overload_pending_high_per_cpu": 1e12,
    "overload_pending_low_per_cpu": 1e12,
    "log_to_driver": False,
}

N_NODES = 3
CPUS_PER_NODE = 2
WORK_S = 0.08  # per-task sleep -> nominal capacity = 6 CPU / 0.08 = 75/s
TICK_S = 0.1


def nominal_capacity() -> float:
    return N_NODES * CPUS_PER_NODE / WORK_S


def build_cluster(overrides: Dict):
    from ray_tpu.core.config import Config
    from ray_tpu.cluster.cluster_utils import Cluster

    cluster = Cluster(config=Config(dict(overrides)))
    for _ in range(N_NODES):
        cluster.add_node(num_cpus=CPUS_PER_NODE)
    cluster.wait_for_nodes(N_NODES)
    return cluster


def burst_schedule(seed: int, duration_s: float, mult_lo: float,
                   mult_hi: float) -> List[int]:
    """Seeded per-tick burst sizes (tasks per 100ms tick) — byte-identical
    across both arms so the A/B comparison sees the SAME offered trace."""
    rng = random.Random(seed * 7919 + 13)
    cap = nominal_capacity()
    out = []
    for _ in range(int(duration_s / TICK_S)):
        mult = mult_lo + (mult_hi - mult_lo) * rng.random()
        out.append(max(1, int(round(mult * cap * TICK_S))))
    return out


def _chaos_loop(cluster, stop: threading.Event, seed: int,
                kill_period_s: float, stats: Dict):
    """Seeded churn-node kills, each replaced after a beat (capacity
    recovers; in-flight tasks on the victim retry)."""
    rng = random.Random(seed)
    while not stop.wait(kill_period_s * (0.7 + 0.6 * rng.random())):
        try:
            if len(cluster.daemons) < 2:
                continue  # keep a survivor for failover
            cluster.kill_node(rng.choice(cluster.daemons))
            stats["node_kills"] += 1
            time.sleep(0.5)
            cluster.add_node(num_cpus=CPUS_PER_NODE)
        except Exception as e:  # noqa: BLE001 - chaos must not kill the run
            print("chaos error:", repr(e), file=sys.stderr)


def run_phase(bursts: List[int], slo_s: float, chaos: bool, seed: int,
              kill_period_s: float, resolve_full: bool,
              cluster) -> Dict:
    """Drive one open-loop phase against an already-init'd runtime.

    resolve_full: ON-arm semantics — wait for EVERY ref to terminally
    resolve (the zero-silent-drop gate). The OFF arm instead bounds each
    wait at the SLO (+grace): its backlog is unbounded by construction
    and waiting it out would only measure the collector.
    """
    import ray_tpu
    from ray_tpu.core.exceptions import (
        ClusterOverloadedError,
        GetTimeoutError,
    )

    @ray_tpu.remote(num_cpus=1, max_retries=8)
    def storm_task(work_s):
        time.sleep(work_s)
        return time.time()

    # warm the worker pool so phase 1 tasks don't pay process spawns
    ray_tpu.get([storm_task.remote(0.001)
                 for _ in range(N_NODES * CPUS_PER_NODE)], timeout=60)

    stats = {"submitted": 0, "ok_slo": 0, "late": 0, "rejected": 0,
             "errors": 0, "silently_unresolved": 0, "node_kills": 0}
    q: "queue.Queue" = queue.Queue()

    def collector():
        while True:
            item = q.get()
            if item is None:
                return
            ref, submit_ts = item
            timeout = 90.0 if resolve_full else \
                max(0.01, submit_ts + slo_s + 2.0 - time.time())
            try:
                end = ray_tpu.get(ref, timeout=timeout)
            except GetTimeoutError:
                # OFF arm: late-or-never — counted against goodput; the
                # ON arm's 90s bound makes this a SILENT DROP (gated 0)
                stats["silently_unresolved" if resolve_full
                      else "late"] += 1
                continue
            except ClusterOverloadedError:
                stats["rejected"] += 1  # typed terminal outcome
                continue
            except Exception:  # noqa: BLE001 - typed task error
                stats["errors"] += 1
                continue
            # classification by the TASK-stamped completion time, so a
            # lagging collector cannot misclassify
            if end - submit_ts <= slo_s:
                stats["ok_slo"] += 1
            else:
                stats["late"] += 1

    col = threading.Thread(target=collector, daemon=True)
    col.start()
    stop = threading.Event()
    chaos_t = None
    if chaos:
        chaos_t = threading.Thread(
            target=_chaos_loop,
            args=(cluster, stop, seed, kill_period_s, stats), daemon=True,
        )
        chaos_t.start()

    t0 = time.perf_counter()
    next_tick = t0
    for burst in bursts:
        for _ in range(burst):
            ts = time.time()
            ref = storm_task.remote(WORK_S)
            stats["submitted"] += 1
            q.put((ref, ts))
        next_tick += TICK_S
        delay = next_tick - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
    gen_wall = time.perf_counter() - t0
    stop.set()
    if chaos_t is not None:
        chaos_t.join(timeout=kill_period_s * 2)
    q.put(None)
    col.join(timeout=300.0)

    cap = nominal_capacity()
    return {
        "submitted": stats["submitted"],
        "offered_rate": round(stats["submitted"] / max(gen_wall, 1e-9), 1),
        "offered_mult": round(
            stats["submitted"] / max(gen_wall, 1e-9) / cap, 2),
        "gen_wall_s": round(gen_wall, 2),
        "goodput_rps": round(stats["ok_slo"] / max(gen_wall, 1e-9), 1),
        "ok_slo": stats["ok_slo"],
        "late": stats["late"],
        "rejected": stats["rejected"],
        "errors": stats["errors"],
        "silently_unresolved": stats["silently_unresolved"],
        "node_kills": stats["node_kills"],
        "slo_s": slo_s,
    }


def run_storm(seed: int = 7, duration_s: float = 12.0,
              peak_duration_s: float = 6.0, mult_lo: float = 2.0,
              mult_hi: float = 10.0, slo_s: float = 1.5,
              kill_period_s: float = 3.0, ratio_bar: float = 3.0,
              frac_bar: float = 0.6) -> Dict:
    import tempfile

    import ray_tpu
    from ray_tpu.analysis import invariants

    bursts = burst_schedule(seed, duration_s, mult_lo, mult_hi)
    peak_bursts = burst_schedule(seed + 1, peak_duration_s, 0.9, 0.9)
    out: Dict = {
        "seed": seed,
        "nominal_capacity_rps": nominal_capacity(),
        "mult_range": [mult_lo, mult_hi],
    }

    # ---- arm A: control ON (peak phase, then the overload phase),
    # protocol-traced and admission-conservation-checked strict-terminal
    fd, trace_path = tempfile.mkstemp(
        prefix="overload_storm_trace_", suffix=".jsonl")
    import os as _os

    _os.close(fd)
    open(trace_path, "w").close()
    invariants.install(trace_path)
    cluster = build_cluster(CONTROL_ON)
    ray_tpu.init(address=cluster.address, config=dict(CONTROL_ON))
    try:
        out["peak"] = run_phase(peak_bursts, slo_s, chaos=False,
                                seed=seed, kill_period_s=kill_period_s,
                                resolve_full=True, cluster=cluster)
        print("peak:", json.dumps(out["peak"]), flush=True)
        out["overload_on"] = run_phase(
            bursts, slo_s, chaos=True, seed=seed,
            kill_period_s=kill_period_s, resolve_full=True,
            cluster=cluster)
        print("overload ON:", json.dumps(out["overload_on"]), flush=True)
        from ray_tpu.core import api as _api

        # the advisory throttle should have CLEARED by the time the ON
        # arm fully resolved (drained queue -> unthrottle push)
        out["final_overload_state"] = _api._runtime.overload_state()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        invariants.uninstall()
    violations = invariants.check_trace(trace_path, strict_terminal=True)
    out["invariant_violations"] = [v.format() for v in violations]
    print(f"protocol trace: {trace_path} "
          f"({len(violations)} violations, strict-terminal incl. "
          "admission conservation)", flush=True)
    for v in violations:
        print("  " + v.format(), flush=True)

    # ---- arm B: control OFF (same bursts + chaos), the collapse arm
    cluster = build_cluster(CONTROL_OFF)
    ray_tpu.init(address=cluster.address, config=dict(CONTROL_OFF))
    try:
        out["overload_off"] = run_phase(
            bursts, slo_s, chaos=True, seed=seed,
            kill_period_s=kill_period_s, resolve_full=False,
            cluster=cluster)
        print("overload OFF:", json.dumps(out["overload_off"]),
              flush=True)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()

    on = out["overload_on"]["goodput_rps"]
    off = out["overload_off"]["goodput_rps"]
    peak = out["peak"]["goodput_rps"]
    out["goodput_ratio_on_off"] = round(on / max(off, 1e-9), 2)
    out["on_frac_of_peak"] = round(on / max(peak, 1e-9), 3)
    out["gates"] = {
        "ratio_bar": ratio_bar,
        "frac_bar": frac_bar,
        "offered_ge_2x": out["overload_off"]["offered_mult"] >= 2.0,
        "ratio_ok": out["goodput_ratio_on_off"] >= ratio_bar,
        "frac_ok": out["on_frac_of_peak"] >= frac_bar,
        "zero_silent_drops":
            out["overload_on"]["silently_unresolved"] == 0
            and out["peak"]["silently_unresolved"] == 0,
        "invariants_clean": not out["invariant_violations"],
    }
    out["storm_pass"] = all(out["gates"].values())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--mult-lo", type=float, default=2.0)
    ap.add_argument("--mult-hi", type=float, default=10.0)
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: short phases, 2-4x bursts, relaxed "
                         "ratio/frac bars (shared-box noise), same "
                         "zero-silent-drop + invariant teeth")
    ap.add_argument("--json", default=None, metavar="FILE")
    args = ap.parse_args(argv)

    if args.smoke:
        rec = run_storm(seed=args.seed, duration_s=6.0,
                        peak_duration_s=3.0, mult_lo=3.0, mult_hi=6.0,
                        slo_s=1.2, kill_period_s=3.0, ratio_bar=2.0,
                        frac_bar=0.5)
    else:
        rec = run_storm(seed=args.seed, duration_s=args.duration,
                        mult_lo=args.mult_lo, mult_hi=args.mult_hi)
    print("storm:", json.dumps(rec), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rec, f, indent=2)
        print("record ->", args.json, flush=True)
    print("OVERLOAD STORM:", "GREEN" if rec["storm_pass"] else "RED",
          flush=True)
    return 0 if rec["storm_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
