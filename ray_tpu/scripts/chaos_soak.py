"""Chaos soak: continuous task/actor/PG load under a seeded fault plane.

Not a pytest test (runtime is minutes by design): run as
    python -m ray_tpu.scripts.chaos_soak [--seed N] [--duration S]
and read the rolling stats. Every task result is value-checked; "errors"
must stay 0 — expected_actor_errs counts actor calls in flight at a node
kill (at-most-once semantics, reference behavior).

The fault plane is a ray_tpu.chaos.FaultSchedule: node kills fire from
seeded kill rules consulted once per loop iteration (the step() hook), and
frame-level faults (driver->GCS resets, daemon->GCS drops) ride the RPC
hook points. The workload mix is driven by the same seed, so two runs with
one seed replay the same soak — compare their sched.trace_text() to verify.
Every run is also protocol-traced and invariant-checked post-hoc
(analysis/invariants.py): the process exits 1 on any exactly-once /
capacity-conservation / 2PC / ordering violation.
Last recorded run (2026-08-04, 2-core host, seed 7, invariant tracing on,
``--serve`` mix): 45s, 469 tasks, 164 actor calls, 44 PGs, 22 node
kills, 82 verified fast-path serve responses with 0 LOST and 0 DUPLICATE
deliveries (2 error responses while the replica pool was mid-respawn —
delivered outcomes, within budget), 0 task errors, 0 invariant
violations, 160 interleaving-coverage pairs. (Prior ``--dag`` run
2026-08-03: 75s, 237 tasks, 79 actor calls, 23 PGs, 10 node kills, 20
compiled-DAG iterations with 3 kill-forced rebuilds, 0 errors, 0
violations.)
``--race`` run (2026-08-04, seed 7, whole soak under the happens-before
race sanitizer — 110 watched fields, every lock/thread/queue/executor
edge vector-clocked): 45s, 583 tasks, 218 actor calls, 55 PGs, 22 node
kills, 0 task errors, 0 RACES, 0 invariant violations, 99
interleaving-coverage pairs. (The racer's first soaks found and fixed 5
real races — see analysis/racer.py and tests/test_racer.py; this run is
the clean baseline after those fixes.)
"""
import argparse
import os
import random
import time

import numpy as np

import ray_tpu
from ray_tpu import chaos
from ray_tpu.cluster.cluster_utils import Cluster

ap = argparse.ArgumentParser(description=__doc__)
ap.add_argument("--seed", type=int, default=7,
                help="fault-schedule + workload seed (same seed = same soak)")
ap.add_argument("--duration", type=float, default=600.0, help="seconds")
ap.add_argument("--trace", default=None, metavar="FILE",
                help="protocol-trace JSONL path (default: a fresh temp "
                     "file); the run is invariant-checked post-hoc and "
                     "exits 1 on violations")
ap.add_argument("--dag", action="store_true",
                help="mix a compiled-DAG pipeline into the workload: "
                     "iterations ride shm channels; node kills break the "
                     "pipeline (ChannelClosedError) and it is torn down "
                     "and recompiled — exercising the rpc_dag_* plane "
                     "under churn")
ap.add_argument("--bursty", action="store_true",
                help="mix seeded submission BURSTS into the workload and "
                     "arm the overload control plane (tight per-driver "
                     "admission bound + pacing + advisory throttle): "
                     "bursts overrun the bound, rejections pace-and-"
                     "retry, and every task still terminally resolves — "
                     "typed ClusterOverloadedError outcomes are counted "
                     "separately, never as errors")
ap.add_argument("--race", action="store_true",
                help="run the whole soak under the happens-before race "
                     "sanitizer (analysis/racer.py): every watched "
                     "control-plane field proxy-instrumented, every "
                     "lock/thread/queue/executor edge vector-clocked; "
                     "EXITS 1 on any detected race, with both access "
                     "stacks in a race-*.jsonl artifact")
ap.add_argument("--stall", action="store_true",
                help="run the whole soak under the wait-graph deadlock & "
                     "stall sanitizer (analysis/waitgraph.py): every "
                     "lock/queue/future/executor wait and channel park "
                     "edges into a live cross-thread wait-for graph; "
                     "EXITS 1 on any deadlock report or any unattributed "
                     "stall > 30s, with stacks in a waitgraph-*.jsonl "
                     "artifact")
ap.add_argument("--gray", action="store_true",
                help="mix seeded gray failures into the fault plane: a "
                     "probabilistic chaos ``slow`` rule stretches task "
                     "executions 12x on whatever node they land on, with "
                     "the gray defense plane armed fast (250ms sweeps, "
                     "2-sweep quarantine sustain, 0.5s probes) — "
                     "exercising suspicion scoring, speculation, and the "
                     "quarantine/probation lifecycle under node churn; "
                     "slowed tasks still terminally resolve, so the 0-"
                     "errors gate is unchanged")
ap.add_argument("--serve", action="store_true",
                help="mix serve fast-path deployments into the workload: "
                     "bursts of channel-plane requests against "
                     "fast_path=True replicas while nodes die; prints "
                     "goodput + rerouted/duplicate counts and EXITS 1 on "
                     "any duplicate or lost response (exactly-once "
                     "delivery under churn)")
args = ap.parse_args()

# Every soak run is invariant-checked post-hoc (analysis/invariants.py):
# "survived" means exactly-once task_done, conserved capacity, legal PG
# 2PC, ordered actor execs — not just "didn't crash".
from ray_tpu.analysis import invariants

if args.trace:
    trace_path = args.trace
    # the tracer appends; a leftover file from a previous run would feed
    # stale events into this run's invariant check
    open(trace_path, "w").close()
else:
    import tempfile

    _fd, trace_path = tempfile.mkstemp(
        prefix="chaos_soak_trace_", suffix=".jsonl"
    )
    import os as _os

    _os.close(_fd)
invariants.install(trace_path)

# --race: the dynamic half of the hybrid race sanitizer rides the whole
# soak. Installed BEFORE the cluster exists so every lock/thread/queue
# the control plane allocates is instrumented from birth.
race_san = None
if args.race:
    from ray_tpu.analysis import racer as _racer

    race_san = _racer.RaceSanitizer().install()
    assert not race_san.unresolved, race_san.unresolved

# --stall: the wait-graph sanitizer rides the whole soak maintaining the
# live wait-for graph. Installed BEFORE the cluster exists (same rule as
# the racer: every lock/queue/executor/channel the control plane
# allocates must be instrumented from birth); its stall watchdog
# attributes any wait older than 30s into waitgraph-*.jsonl artifacts.
wait_san = None
if args.stall:
    from ray_tpu.analysis import waitgraph as _waitgraph

    wait_san = _waitgraph.WaitSanitizer(stall_warn_s=30.0).install()

# Per-operation RPC accounting rides the whole soak (analysis/rpcflow):
# installed LAST so it wraps whichever tracer is active (the invariant
# file tracer, or the race sanitizer when --race) and delegates every
# hook to it. The exit table prints frames/op against the committed
# budget; an order-of-magnitude breach fails the soak.
from ray_tpu.analysis import rpcflow as _rpcflow

rpc_prof = _rpcflow.RpcProfiler().install()
rpc_budget = _rpcflow.load_budget(
    os.path.join(_rpcflow.repo_root(), _rpcflow.DEFAULT_BUDGET_FILE))

rng = random.Random(args.seed)  # workload mix (tasks vs actors vs PGs)
_rules = [
    # ~1 node kill per 25 loop iterations, deterministic per seed
    chaos.kill(label="soak", p=0.04, target="churn"),
    # occasional driver->GCS resets exercise the reconnect plane
    chaos.reset(src="driver-*", dst="gcs", p=0.002, hook="client_send"),
    # lossy daemon->GCS link exercises call retries
    chaos.drop(src="node-*", dst="gcs", p=0.001, hook="client_send"),
]
if args.gray:
    # seeded gray failures: ~3% of executions run 12x slow, anywhere —
    # enough to light up suspicion/speculation/probation without wedging
    # any task past its get() timeout (0.02s * 12 << 60s)
    _rules.append(chaos.slow(node="*", factor=12.0, p=0.03))
sched = chaos.install(chaos.FaultSchedule(seed=args.seed, rules=_rules))

_overrides = {}
if args.gray:
    # arm the defense plane fast so the short soak actually cycles the
    # quarantine/probation lifecycle (the probe path is also chaos-slowed
    # by the same rule, so sticky quarantine gets exercised too)
    _overrides.update({
        "health_check_period_ms": 250.0,
        "quarantine_sustain_sweeps": 2,
        "probe_interval_s": 0.5,
        "speculation_min_elapsed_s": 0.15,
    })
if args.bursty:
    # arm the overload control plane so the burst mix exercises it: a
    # small per-driver admission bound, fast pacing, and low throttle
    # thresholds (the soak gate still requires 0 task errors — typed
    # overload rejections are budgeted separately below)
    _overrides.update({
        "admission_max_pending_per_driver": 48,
        "admission_retry_after_s": 0.1,
        "admission_pacing_enabled": True,
        "admission_pacing_max_s": 60.0,
        "overload_pending_high_per_cpu": 6.0,
        "overload_pending_low_per_cpu": 2.0,
    })
from ray_tpu.core.config import Config as _Config

cluster = Cluster(config=_Config(dict(_overrides)))
# STABLE resource: the --serve mix pins the serve controller here so the
# control plane survives churn-node kills (replicas still float and die)
stable = cluster.add_node(num_cpus=2, node_id="stable",
                          resources={"STABLE": 100})
for _ in range(2):
    cluster.add_node(num_cpus=2)


_kill_lock = __import__("threading").Lock()


def kill_one_churn_node():
    # each fired kill rule runs on its own thread; overlapping invocations
    # would double-kill one victim and over-grow the replacement pool
    if not _kill_lock.acquire(blocking=False):
        return
    try:
        victims = [d for d in cluster.daemons if d.node_id != "stable"]
        if len(victims) < 2:
            return  # keep at least one churn node alive for in-flight work
        cluster.kill_node(victims[0])
        stats["kills"] += 1
        time.sleep(0.5)
        cluster.add_node(num_cpus=2)
    finally:
        _kill_lock.release()


sched.register_kill("churn", kill_one_churn_node)
ray_tpu.init(address=cluster.address, config=dict(_overrides) or None)

@ray_tpu.remote(max_retries=8)
def work(i, payload):
    time.sleep(0.02)
    return int(payload.sum()) + i

@ray_tpu.remote(max_restarts=-1)
class Counter:
    def __init__(self): self.n = 0
    def add(self, k): self.n += k; return self.n

from ray_tpu.util.placement_group import placement_group, remove_placement_group

actors = [Counter.remote() for _ in range(4)]

# --- optional compiled-DAG mix (--dag): a 2-stage pipeline driven through
# its channels; a node kill mid-iteration surfaces as ChannelClosedError
# (never a hang) and the pipeline is recompiled on surviving nodes ---
# --- optional serve fast-path mix (--serve): a fast_path=True deployment
# driven in small bursts; node/replica deaths must reroute in-flight
# requests with EXACTLY-ONCE delivery (duplicates or losses fail the soak)
serve_h = None
if args.serve:
    from ray_tpu import serve as _serve
    from ray_tpu.serve import api as _serve_api

    _serve_api.CONTROLLER_OPTIONS = {"resources": {"STABLE": 0.01}}

    @_serve.deployment(num_replicas=2, fast_path=True, name="soak_model")
    def soak_model(x):
        return x * 7 + 3

    serve_h = _serve.run(soak_model.bind(), name="soak", route_prefix=None)
    assert serve_h.remote(1).result(timeout=30) == 10

dag_c = None
if args.dag:
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.1)
    def dag_inc(x): return x + 1

    @ray_tpu.remote(num_cpus=0.1)
    def dag_dbl(x): return x * 2

    def build_dag():
        with InputNode() as inp:
            return dag_dbl.bind(dag_inc.bind(inp)).compile()

    dag_c = build_dag()

t_end = time.time() + args.duration
stats = {"tasks": 0, "actor_calls": 0, "pgs": 0, "kills": 0, "errors": 0,
         "expected_actor_errs": 0, "dag_iters": 0, "dag_rebuilds": 0,
         "serve_ok": 0, "serve_errors": 0, "serve_lost": 0,
         "bursts": 0, "overload_rejects": 0}
last_report = time.time()
payload = np.arange(1000)
pending = []
i = 0
while time.time() < t_end:
    i += 1
    sched.step("soak")  # kill-at-step hook: seeded node churn
    r = rng.random()
    try:
        if r < 0.6:
            pending.append(("task", work.remote(i, payload), i))
        elif r < 0.85:
            a = rng.choice(actors)
            pending.append(("actor", a.add.remote(1), None))
        elif r < 0.91:
            pg = placement_group([{"CPU": 1}], strategy="PACK")
            pg.ready(timeout=10)
            remove_placement_group(pg)
            stats["pgs"] += 1
        elif args.bursty and r < 0.94:
            # a seeded submission BURST past the admission bound: the
            # control plane must pace-and-retry (or reject TYPED) — each
            # ref still terminally resolves when drained below
            for k in range(40):
                pending.append(("task", work.remote(i * 100 + k, payload),
                                i * 100 + k))
            stats["bursts"] += 1
        elif args.serve and r >= 0.97:
            # a burst of fast-path requests (submit all, then collect):
            # overlapping requests are what reroute-on-death must cover
            xs = [i * 10 + k for k in range(4)]
            resps = [(x, serve_h.remote(x)) for x in xs]
            for x, resp in resps:
                try:
                    v = resp.result(timeout=20)
                    if v != x * 7 + 3:
                        stats["errors"] += 1
                        print("SERVE VALUE ERROR:", v, "want", x * 7 + 3,
                              flush=True)
                    else:
                        stats["serve_ok"] += 1
                except Exception as e:
                    from ray_tpu.core.exceptions import GetTimeoutError

                    if isinstance(e, GetTimeoutError):
                        stats["serve_lost"] += 1  # no response at all
                        print("SERVE LOST:", repr(e)[:120], flush=True)
                    else:
                        # replica pool momentarily empty mid-churn: an
                        # ERROR response is a delivered outcome, not a loss
                        stats["serve_errors"] += 1
        elif args.dag and r < 0.97:
            try:
                if dag_c is None:
                    dag_c = build_dag()
                    stats["dag_rebuilds"] += 1
                v = dag_c.execute(i, timeout=30.0)
                if v != (i + 1) * 2:
                    # a WRONG value is data corruption, never churn — it
                    # must fail the soak, not vanish into a rebuild
                    stats["errors"] += 1
                    print("DAG VALUE ERROR:", v, "want", (i + 1) * 2,
                          flush=True)
                stats["dag_iters"] += 1
            except Exception:
                # pipeline broken by churn: release it; rebuilt on the
                # next dag tick (capacity may need a replacement node)
                try:
                    dag_c.teardown()
                except Exception:  # noqa: BLE001
                    pass
                dag_c = None
        # drain some pending
        while len(pending) > 60:
            kind, ref, arg = pending.pop(0)
            try:
                v = ray_tpu.get(ref, timeout=60)
                if kind == "task":
                    assert v == int(payload.sum()) + arg, (v, arg)
                    stats["tasks"] += 1
                else:
                    stats["actor_calls"] += 1
            except Exception as e:
                from ray_tpu.core.exceptions import ClusterOverloadedError

                if kind == "actor":
                    stats["expected_actor_errs"] += 1  # calls in flight at node death
                elif isinstance(e, ClusterOverloadedError):
                    # typed admission outcome (--bursty): a DELIVERED
                    # rejection, the overload contract — never an error
                    stats["overload_rejects"] += 1
                else:
                    stats["errors"] += 1
                    print("TASK ERROR:", repr(e)[:200], flush=True)
    except Exception as e:
        stats["errors"] += 1
        print("LOOP ERROR:", repr(e)[:200], flush=True)
    if time.time() - last_report > 30:
        print("t=%.0fs %s pending=%d" % (
            args.duration - (t_end - time.time()), stats, len(pending)
        ), flush=True)
        last_report = time.time()

for kind, ref, arg in pending:
    try:
        ray_tpu.get(ref, timeout=90)
        stats["tasks" if kind == "task" else "actor_calls"] += 1
    except Exception as e:
        from ray_tpu.core.exceptions import ClusterOverloadedError

        if kind == "actor":
            stats["expected_actor_errs"] += 1
        elif isinstance(e, ClusterOverloadedError):
            stats["overload_rejects"] += 1
        else:
            stats["errors"] += 1
if dag_c is not None:
    try:
        dag_c.teardown()
    except Exception:  # noqa: BLE001
        pass
serve_dups = 0
if serve_h is not None:
    fps = serve_h.fastpath_stats() or {}
    serve_dups = fps.get("duplicates", 0)
    print("serve fastpath:", fps, "lost:", stats["serve_lost"], flush=True)
    from ray_tpu import serve as _serve2

    _serve2.shutdown()
print("FINAL:", stats, flush=True)
totals = [ray_tpu.get(a.add.remote(0), timeout=60) for a in actors]
print("actor totals:", totals, flush=True)
print("fault trace (%d faults):" % len(sched.trace()), flush=True)
print(sched.trace_text(), flush=True)

# final metrics snapshot (ray_tpu.obs): printed + written to artifacts/ so
# soak regressions (latency shifts, retry storms) are diffable across runs
from ray_tpu.util import metrics as _metrics

_prom = _metrics.export_prometheus()
_metrics_path = None
try:
    import os as _os2

    _os2.makedirs("artifacts", exist_ok=True)
    _metrics_path = _os2.path.join(
        "artifacts", "chaos_soak_metrics_seed%d.prom" % args.seed
    )
    with open(_metrics_path, "w") as _f:
        _f.write(_prom)
except OSError:
    pass
print("metrics snapshot (%d series lines -> %s):" % (
    sum(1 for ln in _prom.splitlines() if ln and not ln.startswith("#")),
    _metrics_path,
), flush=True)
print("\n".join(
    ln for ln in _prom.splitlines()
    if ln.startswith(("ray_tpu_rpc_reconnects", "ray_tpu_rpc_resends",
                      "ray_tpu_rpc_blackhole", "ray_tpu_gcs_sched_round_s_c",
                      "ray_tpu_client_tasks_submitted",
                      "ray_tpu_gcs_quarantined_nodes",
                      "ray_tpu_gcs_speculative"))
), flush=True)

ray_tpu.shutdown(); cluster.shutdown(); chaos.uninstall()
rpc_prof.uninstall()  # first in, last out: restores the wrapped tracer
races = []
if race_san is not None:
    race_san.uninstall()
    races = race_san.races
    print("race sanitizer: %d race(s) over %d watched fields"
          % (len(races), race_san.report()["watched_fields"]), flush=True)
    if races:
        print(race_san.format_races(), flush=True)
        print("race artifact:", race_san.dump("chaos-soak"), flush=True)
deadlocks, bad_stalls = [], []
if wait_san is not None:
    wait_san.uninstall()
    deadlocks = wait_san.deadlocks
    # queue/cond idle-consumer waits and attributed channel parks are
    # soak noise; a LOCK/future/rpc wait no one resolvably holds for
    # >30s is a liveness failure even if it eventually unwedged
    bad_stalls = [s for s in wait_san.stalls
                  if s.get("unattributed") and s.get("age_s", 0.0) > 30.0]
    print("wait sanitizer: %d deadlock report(s), %d stall report(s) "
          "(%d unattributed > 30s)"
          % (len(deadlocks), len(wait_san.stalls), len(bad_stalls)),
          flush=True)
    if deadlocks or bad_stalls:
        print("waitgraph artifact:", wait_san.dump("chaos-soak"),
              flush=True)
invariants.uninstall()
violations = invariants.check_trace(trace_path)
print("protocol trace: %s (%d violations)" % (trace_path, len(violations)),
      flush=True)
for v in violations:
    print("  " + v.format(), flush=True)
# interleaving coverage: distinct ordered adjacent handler pairs the GCS
# actually observed — the same coverage language the deterministic
# explorer reports (analysis/explore.py), so a soak and an exploration
# are comparable: a pair neither produced was never tested by either
from ray_tpu.analysis.explore import interleaving_coverage

pairs = interleaving_coverage(invariants.read_trace(trace_path))
print("interleaving coverage: %d distinct handler-pair orderings "
      "observed at the GCS" % len(pairs), flush=True)
# per-operation RPC table: frames/op over the whole soak vs the committed
# budget. Chaos repair traffic (reroutes, resend-after-reset, reroute
# re-registration) legitimately exceeds the quiet steady-state ceiling,
# so the soak only FAILS on an order-of-magnitude breach (> 3x budget
# + 1 — the N+1 regrowth class); the exact ceiling is enforced on a
# quiet cluster by `lint_gate --rpc-budget`.
rpc_per_op = rpc_prof.per_op_rpcs()
rpc_snap = rpc_prof.snapshot()
print("per-operation RPC table (frames/op over the soak):", flush=True)
print("  " + _rpcflow.budget_table(rpc_per_op).replace("\n", "\n  "),
      flush=True)
print("  unattributed (background planes): %d calls, %d pushes"
      % (rpc_snap["unattributed"]["calls"],
         rpc_snap["unattributed"]["pushes"]), flush=True)
rpc_over = []
for _op, _entry in sorted(rpc_budget.items()):
    _got = rpc_per_op.get(_op)
    if _got is not None and _got > float(_entry["rpcs"]) * 3 + 1:
        rpc_over.append("%s: %.2f frames/op vs budget %g (>3x+1)"
                        % (_op, _got, float(_entry["rpcs"])))
for _line in rpc_over:
    print("RPC BUDGET BREACH: " + _line, flush=True)
print("SOAK DONE; task errors:", stats["errors"], flush=True)
if serve_h is not None and (serve_dups or stats["serve_lost"]):
    # exactly-once delivery is the --serve mix's contract: any duplicate
    # or lost response is a correctness failure, not churn noise
    print("SERVE EXACTLY-ONCE VIOLATION: duplicates=%d lost=%d"
          % (serve_dups, stats["serve_lost"]), flush=True)
    raise SystemExit(1)
if violations or stats["errors"]:
    # leave a black box in the standard flightrec artifact location: the
    # soak ran under the file tracer (which displaced the in-memory
    # recorder), so the artifact is the trace TAIL in the same
    # --check-trace format a production recorder dump would have
    from ray_tpu.obs import save_trace_tail

    print("flight-recorder black box:",
          save_trace_tail(trace_path, "chaos-soak-error"), flush=True)
if races:
    # the race sanitizer's contract mirrors the invariant checker's:
    # a detected race is a correctness failure, never soak noise
    raise SystemExit(1)
if deadlocks or bad_stalls:
    # the wait sanitizer's contract: a wait cycle or an unattributed
    # >30s stall is a liveness failure, never soak noise
    raise SystemExit(1)
if violations:
    raise SystemExit(1)
if rpc_over:
    # an order-of-magnitude per-op frame breach means a hot path regrew
    # an N+1 (or lost its batching) — a regression, not chaos noise
    raise SystemExit(1)
