"""Chaos soak: continuous task/actor/PG load under node churn.

Not a pytest test (runtime is minutes by design): run as
    python -m ray_tpu.scripts.chaos_soak [seconds]
and read the rolling stats. Every task result is value-checked; "errors"
must stay 0 — expected_actor_errs counts actor calls in flight at a node
kill (at-most-once semantics, reference behavior). Last recorded run
(2026-07-30, 1-core host): 580s, 5278 tasks, 2137 actor calls, 539 PGs,
379 node kills, 0 task errors.
"""
import os, random, sys, time
import numpy as np
import ray_tpu
from ray_tpu.cluster.cluster_utils import Cluster

DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 600.0
random.seed(7)

cluster = Cluster()
stable = cluster.add_node(num_cpus=2, node_id="stable")
churn_nodes = [cluster.add_node(num_cpus=2) for _ in range(2)]
ray_tpu.init(address=cluster.address)

@ray_tpu.remote(max_retries=8)
def work(i, payload):
    time.sleep(random.random() * 0.05)
    return int(payload.sum()) + i

@ray_tpu.remote(max_restarts=-1)
class Counter:
    def __init__(self): self.n = 0
    def add(self, k): self.n += k; return self.n

from ray_tpu.util.placement_group import placement_group, remove_placement_group

actors = [Counter.remote() for _ in range(4)]
t_end = time.time() + DURATION
stats = {"tasks": 0, "actor_calls": 0, "pgs": 0, "kills": 0, "errors": 0,
         "expected_actor_errs": 0}
last_report = time.time()
payload = np.arange(1000)
pending = []
i = 0
while time.time() < t_end:
    i += 1
    r = random.random()
    try:
        if r < 0.55:
            pending.append(("task", work.remote(i, payload), i))
        elif r < 0.8:
            a = random.choice(actors)
            pending.append(("actor", a.add.remote(1), None))
        elif r < 0.86:
            pg = placement_group([{"CPU": 1}], strategy="PACK")
            pg.ready(timeout=10)
            remove_placement_group(pg)
            stats["pgs"] += 1
        elif r < 0.9 and len(cluster.daemons) > 1:
            victim = random.choice([d for d in cluster.daemons if d.node_id != "stable"])
            cluster.kill_node(victim)
            stats["kills"] += 1
            time.sleep(0.5)
            cluster.add_node(num_cpus=2)
        # drain some pending
        while len(pending) > 60:
            kind, ref, arg = pending.pop(0)
            try:
                v = ray_tpu.get(ref, timeout=60)
                if kind == "task":
                    assert v == int(payload.sum()) + arg, (v, arg)
                    stats["tasks"] += 1
                else:
                    stats["actor_calls"] += 1
            except Exception as e:
                if kind == "actor":
                    stats["expected_actor_errs"] += 1  # calls in flight at node death
                else:
                    stats["errors"] += 1
                    print("TASK ERROR:", repr(e)[:200], flush=True)
    except Exception as e:
        stats["errors"] += 1
        print("LOOP ERROR:", repr(e)[:200], flush=True)
    if time.time() - last_report > 30:
        print("t=%.0fs %s pending=%d" % (DURATION - (t_end - time.time()), stats, len(pending)), flush=True)
        last_report = time.time()

for kind, ref, arg in pending:
    try:
        ray_tpu.get(ref, timeout=90)
        stats["tasks" if kind == "task" else "actor_calls"] += 1
    except Exception:
        if kind == "actor":
            stats["expected_actor_errs"] += 1
        else:
            stats["errors"] += 1
print("FINAL:", stats, flush=True)
totals = [ray_tpu.get(a.add.remote(0), timeout=60) for a in actors]
print("actor totals:", totals, flush=True)
ray_tpu.shutdown(); cluster.shutdown()
print("SOAK DONE; task errors:", stats["errors"], flush=True)
