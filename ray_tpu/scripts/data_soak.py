"""Data-pipeline soak: shuffle/groupby pipelines verified exact under node churn.

Run as: python -m ray_tpu.scripts.data_soak [seconds]. Each iteration
runs map -> filter -> random_shuffle -> groupby.sum over 2000-4000 rows
and compares the result against an exact host-side computation, while a
node is killed (and replaced) roughly every other pipeline. Last
recorded run (2026-07-30, 1-core host): 300s, 210 exact pipelines, 107
node kills, 0 errors — multi-stage block lineage reconstructs through
churn.
"""
import random, sys, time
import numpy as np
import ray_tpu
from ray_tpu.cluster.cluster_utils import Cluster
from ray_tpu import data as rdata

DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
random.seed(5)
cluster = Cluster()
cluster.add_node(num_cpus=2, node_id="stable")
cluster.add_node(num_cpus=2)
ray_tpu.init(address=cluster.address)

stats = {"pipelines": 0, "kills": 0, "errors": 0}
t_end = time.time() + DURATION
last = time.time()
it = 0
while time.time() < t_end:
    it += 1
    n = 2000 + (it % 5) * 500
    try:
        ds = rdata.from_items(
            [{"k": i % 10, "v": float(i)} for i in range(n)], parallelism=8
        )
        out = (ds.map(lambda r: {"k": r["k"], "v": r["v"] * 2})
                 .filter(lambda r: r["k"] != 3)
                 .random_shuffle(seed=it)
                 .groupby("k").sum("v"))
        rows = {r["k"]: r["sum(v)"] for r in out.take_all()}
        expect = {}
        for i in range(n):
            if i % 10 != 3:
                expect[i % 10] = expect.get(i % 10, 0.0) + i * 2.0
        assert rows == expect, (sorted(rows.items())[:3], sorted(expect.items())[:3])
        stats["pipelines"] += 1
    except Exception as e:
        stats["errors"] += 1
        print("PIPELINE ERR:", repr(e)[:200], flush=True)
    if random.random() < 0.5 and len(cluster.daemons) > 1:
        victim = random.choice([d for d in cluster.daemons if d.node_id != "stable"])
        cluster.kill_node(victim)
        stats["kills"] += 1
        cluster.add_node(num_cpus=2)
    if time.time() - last > 30:
        print("t=%.0f %s" % (DURATION - (t_end - time.time()), stats), flush=True)
        last = time.time()
print("FINAL:", stats, flush=True)
ray_tpu.shutdown(); cluster.shutdown()
