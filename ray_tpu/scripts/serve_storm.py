"""Serve storm: closed-loop load against the serve fast path under chaos,
with an SLO gate (ISSUE-12 acceptance; recorded as BENCH_serve_r01.json).

    python -m ray_tpu.scripts.serve_storm [--seed N] [--duration S]
        [--clients C] [--replicas R] [--no-chaos] [--compare] [--smoke]
        [--json FILE]

Builds an embedded cluster (one STABLE node pinning the serve controller +
churn nodes), deploys a ``fast_path=True`` synthetic model, and drives it
with C closed-loop client threads while a seeded chaos thread alternates
REPLICA KILLS (worker process of a pair-attached replica) and NODE KILLS
(a churn node, replaced after a beat). Every response is value-checked.

Measured: p50/p99/p999 latency, goodput (verified responses/s), error
budget, and the router's rerouted/duplicate counters. The SLO gate
(``slo_pass``) requires: zero LOST responses (a submitted request whose
result neither arrived nor errored inside its deadline), zero DUPLICATE
deliveries, zero wrong values, error rate within budget (default 1%%),
and p99 under the chaos bound. ``--compare`` also runs the task-layer
serve path (fast_path=False) on the same topology with no chaos and
reports the throughput ratio — the >=5x absorption bar.

Exit code: 0 = SLO green (and, with --compare, ratio >= 5), 1 otherwise.

Last recorded run (2026-08-04, 2-CPU container, seed 7, via
``python bench.py serve_storm``: 20s phases, 48 clients, 3 replicas) —
BENCH_serve_r01.json: task-layer 844 rps (p50 56ms) vs fastpath 5466 rps
(p50 7.8ms) = 6.5x; chaos phase (kill every ~4s): 151495 verified
responses at 7565 rps goodput, p50 5.3ms / p99 17.4ms / p999 74.6ms,
5 replica kills + 1 node kill, 109 rerouted, 0 lost / 0 duplicates /
0 wrong values / 0 errors — SLO green.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from typing import Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def build_cluster(n_churn: int = 2, num_cpus: int = 4):
    """STABLE node (controller pin) + churn nodes (replica fodder)."""
    from ray_tpu.cluster.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=num_cpus, resources={"STABLE": 100},
                     node_id="stable")
    for _ in range(n_churn):
        cluster.add_node(num_cpus=num_cpus)
    cluster.wait_for_nodes(1 + n_churn)
    return cluster


def _deploy(serve, fast_path: bool, replicas: int):
    @serve.deployment(num_replicas=replicas, fast_path=fast_path,
                      max_ongoing_requests=32, name="storm_model")
    def storm_model(x):
        return x * 3 + 1

    return serve.run(storm_model.bind(), name="storm", route_prefix=None)


def _closed_loop(handle, clients: int, duration_s: float,
                 timeout_s: float, stats: Dict, lat: List[float]):
    """C threads, each: submit -> verify -> repeat. Each thread counts
    locally and merges under a lock at exit (the counters are the SLO
    gate's inputs — racing dict `+=` across threads loses updates);
    latencies ride GIL-atomic list.append."""
    stop_at = time.perf_counter() + duration_s
    merge_lock = threading.Lock()

    def worker(k: int):
        local = {"ok": 0, "errors": 0, "lost": 0, "value_errors": 0}
        i = k * 1_000_000
        while time.perf_counter() < stop_at:
            i += 1
            t0 = time.perf_counter()
            try:
                v = handle.remote(i).result(timeout=timeout_s)
            except Exception as e:  # noqa: BLE001 - counted, not fatal
                from ray_tpu.core.exceptions import GetTimeoutError

                if isinstance(e, GetTimeoutError):
                    local["lost"] += 1  # no response inside the deadline
                else:
                    local["errors"] += 1
                continue
            lat.append(time.perf_counter() - t0)
            if v != i * 3 + 1:
                local["value_errors"] += 1
            else:
                local["ok"] += 1
        with merge_lock:
            for key, n in local.items():
                stats[key] += n

    threads = [threading.Thread(target=worker, args=(k,), daemon=True)
               for k in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _chaos_loop(cluster, stop: threading.Event, seed: int,
                kill_period_s: float, stats: Dict):
    """Seeded chaos: alternate replica-worker kills and churn-node kills
    (node replaced after a beat so capacity recovers)."""
    rng = random.Random(seed)
    while not stop.wait(kill_period_s * (0.7 + 0.6 * rng.random())):
        try:
            if rng.random() < 0.6:
                # replica kill: a worker with fast-path pairs attached
                victims = [
                    w
                    for d in cluster.daemons
                    for w in list(d.workers.values())
                    if w.serve_pairs and w.proc is not None
                ]
                if not victims:
                    continue
                rng.choice(victims).proc.kill()
                stats["replica_kills"] += 1
            else:
                churn = [d for d in cluster.daemons
                         if d.node_id != "stable"]
                if len(churn) < 2:
                    continue  # keep one churn node alive for failover
                cluster.kill_node(rng.choice(churn))
                stats["node_kills"] += 1
                time.sleep(0.5)
                cluster.add_node(num_cpus=4)
        except Exception as e:  # noqa: BLE001 - chaos must not kill the run
            print("chaos error:", repr(e), file=sys.stderr)


def run_storm(duration_s: float = 20.0, clients: int = 32,
              replicas: int = 3, chaos: bool = True, seed: int = 7,
              kill_period_s: float = 8.0, timeout_s: float = 30.0,
              fast_path: bool = True, cluster=None,
              error_budget: float = 0.01, p99_bound_s: float = 2.0) -> Dict:
    """One storm phase; returns the measured record (see module doc)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    own_cluster = cluster is None
    if own_cluster:
        cluster = build_cluster()
    serve_api.CONTROLLER_OPTIONS = {"resources": {"STABLE": 0.01}}
    ray_tpu.init(address=cluster.address,
                 config={"log_to_driver": False})
    stats = {"ok": 0, "errors": 0, "lost": 0, "value_errors": 0,
             "replica_kills": 0, "node_kills": 0}
    lat: List[float] = []
    try:
        handle = _deploy(serve, fast_path, replicas)
        assert handle.remote(1).result(timeout=30.0) == 4  # warm
        stop = threading.Event()
        chaos_t = None
        if chaos:
            chaos_t = threading.Thread(
                target=_chaos_loop,
                args=(cluster, stop, seed, kill_period_s, stats),
                daemon=True,
            )
            chaos_t.start()
        t0 = time.perf_counter()
        _closed_loop(handle, clients, duration_s, timeout_s, stats, lat)
        wall = time.perf_counter() - t0
        stop.set()
        if chaos_t is not None:
            chaos_t.join(timeout=kill_period_s * 2)
        fp = handle.fastpath_stats() if fast_path else None
    finally:
        serve.shutdown()
        serve_api.CONTROLLER_OPTIONS = {}
        ray_tpu.shutdown()
        if own_cluster:
            cluster.shutdown()
    lat.sort()
    total = stats["ok"] + stats["errors"] + stats["lost"] \
        + stats["value_errors"]
    error_rate = (stats["errors"] + stats["value_errors"]) / max(total, 1)
    rec = {
        "fast_path": fast_path,
        "chaos": chaos,
        "seed": seed,
        "duration_s": round(wall, 2),
        "clients": clients,
        "replicas": replicas,
        "requests": total,
        "ok": stats["ok"],
        "errors": stats["errors"],
        "lost": stats["lost"],
        "value_errors": stats["value_errors"],
        "replica_kills": stats["replica_kills"],
        "node_kills": stats["node_kills"],
        "goodput_rps": round(stats["ok"] / max(wall, 1e-9), 1),
        "error_rate": round(error_rate, 5),
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 2),
        "p999_ms": round(_percentile(lat, 0.999) * 1e3, 2),
        "rerouted": (fp or {}).get("rerouted", 0),
        "duplicates": (fp or {}).get("duplicates", 0),
    }
    rec["slo_pass"] = bool(
        stats["lost"] == 0
        and rec["duplicates"] == 0
        and stats["value_errors"] == 0
        and error_rate <= error_budget
        and (not chaos or rec["p99_ms"] <= p99_bound_s * 1e3)
        and stats["ok"] > 0
    )
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--clients", type=int, default=48)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--no-chaos", action="store_true",
                    help="pure throughput run, no kills")
    ap.add_argument("--compare", action="store_true",
                    help="also run the task-layer serve path (no chaos) "
                         "and report fastpath/task throughput ratio "
                         "(gate: >= 5x)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: short phases, relaxed p99 bound "
                         "(shared-box scheduling noise), same zero-lost/"
                         "zero-dup/zero-wrong gates")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="write the full record as JSON")
    args = ap.parse_args(argv)

    duration = 6.0 if args.smoke else args.duration
    p99_bound = 10.0 if args.smoke else 2.0
    kill_period = 2.0 if args.smoke else 8.0
    out: Dict = {"seed": args.seed}

    if args.compare:
        base = run_storm(duration_s=duration, clients=args.clients,
                         replicas=args.replicas, chaos=False,
                         seed=args.seed, fast_path=False)
        print("task-layer baseline:", json.dumps(base), flush=True)
        out["task_layer"] = base
        fast = run_storm(duration_s=duration, clients=args.clients,
                         replicas=args.replicas, chaos=False,
                         seed=args.seed, fast_path=True)
        print("fastpath no-chaos:", json.dumps(fast), flush=True)
        out["fastpath"] = fast
        ratio = fast["goodput_rps"] / max(base["goodput_rps"], 1e-9)
        out["speedup"] = round(ratio, 2)
        print(f"speedup: {out['speedup']}x (gate >= 5)", flush=True)

    storm = run_storm(duration_s=duration, clients=args.clients,
                      replicas=args.replicas, chaos=not args.no_chaos,
                      seed=args.seed, kill_period_s=kill_period,
                      p99_bound_s=p99_bound)
    print("storm:", json.dumps(storm), flush=True)
    out["storm"] = storm

    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
        print("record ->", args.json, flush=True)

    ok = storm["slo_pass"] and (
        not args.compare or out["speedup"] >= 5.0
    )
    print("SLO:", "GREEN" if ok else "RED", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
