"""Gray storm: seeded gray-failure A/B over the defense plane (ISSUE-17
acceptance; recorded as BENCH_gray_r01.json).

    python -m ray_tpu.scripts.gray_storm [--seed N] [--waves N]
        [--slow-factor X] [--smoke] [--json FILE]

Topology: 5 nodes x 2 CPU with deterministic node ids; a seeded chaos
``slow`` rule stretches task execution 25x on 2 of the 5 nodes (the
nodes stay ALIVE on heartbeats — the canonical gray failure). The
workload is barrier waves of cluster-width gangs: submit one task per
CPU, wait for all, repeat — so, exactly as in the motivating failure
mode, each wave's latency collapses to its slowest replica.

Two arms on the SAME seeded slow-node trace:

1. **defense ON** — health scoring folds the slow nodes' duration EMAs
   into suspicion, quarantines them after the sustain window, probes
   keep them quarantined (the probe itself is slowed by the same rule),
   and straggler speculation re-runs wedged in-flight tasks on healthy
   nodes. The run is protocol-traced; the invariant checker replays it
   strict-terminal with the speculation invariants armed (exactly-one
   winning task_done apply, cancel-conservation on losers).
2. **defense OFF** — ``gray_defense_enabled: false``: same rules, same
   waves; every wave keeps paying the 25x replica.

Both arms exclude the same warmup-wave prefix from the latency stats:
the ON arm needs a few sweeps of completions before suspicion can see
the gray nodes (the defense *engaging* is what's under test; the bars
measure the recovered steady state).

Gates (``--smoke`` shrinks the run, same teeth): OFF p99 >= p99_bar x
ON p99 (3x), ON goodput >= goodput_bar x OFF (2x), every submission in
BOTH arms terminally resolved, 0 invariant violations (incl. duplicate
task_done applies), >= 1 node actually quarantined in the ON arm.
Exit code: 0 = green, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

N_NODES = 5
CPUS_PER_NODE = 2
N_SLOW = 2
WORK_S = 0.05

# defense ON: fast sweeps + short sustain so quarantine engages within
# the warmup prefix on a tiny cluster; speculation floor above WORK_S so
# healthy tasks are never eligible
CONTROL_ON = {
    "gray_defense_enabled": True,
    "health_check_period_ms": 250.0,
    "quarantine_sustain_sweeps": 2,
    "probe_interval_s": 0.5,
    "speculation_quantile_factor": 3.0,
    "speculation_min_elapsed_s": 0.3,
    "log_to_driver": False,
}
CONTROL_OFF = {
    "gray_defense_enabled": False,
    "health_check_period_ms": 250.0,
    "log_to_driver": False,
}


def node_ids() -> List[str]:
    return [f"gray-{i}" for i in range(N_NODES)]


def slow_spec(seed: int, factor: float) -> Dict:
    """Chaos spec slowing the LAST ``N_SLOW`` nodes by ``factor`` on
    every execution (p=1.0: gray, not flaky). Exported via the
    RAY_TPU_CHAOS_SPEC env payload so worker subprocesses join the same
    fault plane; byte-identical across both arms."""
    from ray_tpu import chaos

    # first-match-wins: the method-scoped inf rule (wedge_task on the
    # last slow node wedges FOREVER — the speculation-rescue phase)
    # shadows the generic 25x rule for that one class only
    rules = [chaos.slow(node=node_ids()[-1], factor=float("inf"),
                        p=1.0, method="wedge_task")]
    rules += [chaos.slow(node=nid, factor=factor, p=1.0)
              for nid in node_ids()[-N_SLOW:]]
    from ray_tpu.chaos.schedule import FaultSchedule

    return FaultSchedule(seed=seed, rules=rules).to_spec()


def build_cluster(overrides: Dict):
    from ray_tpu.core.config import Config
    from ray_tpu.cluster.cluster_utils import Cluster

    cluster = Cluster(config=Config(dict(overrides)))
    for nid in node_ids():
        cluster.add_node(num_cpus=CPUS_PER_NODE, node_id=nid)
    cluster.wait_for_nodes(N_NODES)
    return cluster


def run_arm(n_waves: int, warmup_waves: int, slo_s: float) -> Dict:
    """Drive barrier waves; per-task end-to-end latencies from the
    task-stamped completion time (collector-lag independent)."""
    import threading

    import ray_tpu
    from ray_tpu.core.exceptions import GetTimeoutError

    @ray_tpu.remote(num_cpus=1, max_retries=4)
    def gang_task(work_s):
        time.sleep(work_s)
        return True

    wave_width = N_NODES * CPUS_PER_NODE
    lat: List[float] = []          # measured waves only
    warm_lat: List[float] = []
    stats = {"submitted": 0, "resolved": 0, "errors": 0,
             "silently_unresolved": 0}
    lock = threading.Lock()

    def collect(ref, submit_ts: float, sink: List[float]) -> None:
        # latency is stamped DRIVER-side at resolution: a chaos-stalled
        # execution stalls after the fn body, so a task-side stamp would
        # hide exactly the gray slowness under test
        try:
            ray_tpu.get(ref, timeout=120.0)
            dt = time.time() - submit_ts
            with lock:
                stats["resolved"] += 1
                sink.append(dt)
        except GetTimeoutError:
            with lock:
                stats["silently_unresolved"] += 1
        except Exception:  # noqa: BLE001 - typed task error, terminal
            with lock:
                stats["errors"] += 1
                stats["resolved"] += 1

    t_meas0 = None
    t0 = time.perf_counter()
    for w in range(n_waves):
        if w == warmup_waves:
            t_meas0 = time.perf_counter()
        submit_ts = time.time()
        refs = [gang_task.remote(WORK_S) for _ in range(wave_width)]
        stats["submitted"] += wave_width
        sink = lat if w >= warmup_waves else warm_lat
        threads = [threading.Thread(target=collect,
                                    args=(ref, submit_ts, sink),
                                    daemon=True)
                   for ref in refs]
        for t in threads:
            t.start()
        for t in threads:  # wave barrier
            t.join(timeout=150.0)
    wall = time.perf_counter() - t0
    meas_wall = time.perf_counter() - (t_meas0 or t0)

    lat.sort()

    def pct(q: float) -> float:
        if not lat:
            return float("nan")
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    ok_slo = sum(1 for x in lat if x <= slo_s)
    return {
        "waves": n_waves,
        "warmup_waves": warmup_waves,
        "submitted": stats["submitted"],
        "resolved": stats["resolved"],
        "errors": stats["errors"],
        "silently_unresolved": stats["silently_unresolved"],
        "wall_s": round(wall, 2),
        "p50_s": round(pct(0.50), 4),
        "p95_s": round(pct(0.95), 4),
        "p99_s": round(pct(0.99), 4),
        "max_s": round(max(lat), 4) if lat else float("nan"),
        "ok_slo": ok_slo,
        "goodput_rps": round(ok_slo / max(meas_wall, 1e-9), 1),
        "slo_s": slo_s,
    }


def run_wedge_phase(deadline_s: float) -> Dict:
    """Straggler-speculation rescue: one cluster-width gang of a class
    the chaos spec wedges FOREVER on the last slow node. Without
    speculation those refs never resolve (the node stays ALIVE on
    heartbeats — retries never trigger); the defense must re-run them on
    healthy nodes within the deadline."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def wedge_task(work_s):
        time.sleep(work_s)
        return True

    t0 = time.perf_counter()
    refs = [wedge_task.remote(0.02)
            for _ in range(N_NODES * CPUS_PER_NODE)]
    resolved = unresolved = 0
    for ref in refs:
        budget = max(0.1, deadline_s - (time.perf_counter() - t0))
        try:
            ray_tpu.get(ref, timeout=budget)
            resolved += 1
        except Exception:  # noqa: BLE001 - timeout = not rescued
            unresolved += 1
    return {
        "submitted": len(refs),
        "resolved": resolved,
        "unresolved": unresolved,
        "deadline_s": deadline_s,
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def _trace_spec_stats(trace_path: str) -> Dict:
    """Speculation activity observed in the protocol trace."""
    launched = cancels = promotes = quarantines = 0
    with open(trace_path) as f:
        for line in f:
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            k = ev.get("k")
            if k == "dispatch" and ev.get("speculative"):
                launched += 1
            elif k == "spec_cancel":
                cancels += 1
            elif k == "spec_promote":
                promotes += 1
            elif k == "node_quarantine" and ev.get("quarantined"):
                quarantines += 1
    return {"speculative_launches": launched, "spec_cancels": cancels,
            "spec_promotes": promotes, "quarantine_events": quarantines}


def run_storm(seed: int = 7, n_waves: int = 28, warmup_waves: int = 6,
              slow_factor: float = 25.0, slo_s: float = 0.5,
              p99_bar: float = 3.0, goodput_bar: float = 2.0) -> Dict:
    import tempfile

    import ray_tpu
    from ray_tpu import chaos
    from ray_tpu.analysis import invariants

    out: Dict = {
        "seed": seed,
        "topology": f"{N_NODES}x{CPUS_PER_NODE}cpu, "
                    f"{N_SLOW} slowed {slow_factor:g}x",
        "work_s": WORK_S,
        "slow_nodes": node_ids()[-N_SLOW:],
    }
    # same seeded slow-node trace for both arms; workers inherit the env
    os.environ["RAY_TPU_CHAOS_SPEC"] = json.dumps(
        slow_spec(seed, slow_factor))
    # the daemons (and their probe hook) run in THIS process: install here
    chaos.install_from_env()

    # ---- arm A: defense ON, protocol-traced, strict-terminal checked
    fd, trace_path = tempfile.mkstemp(
        prefix="gray_storm_trace_", suffix=".jsonl")
    os.close(fd)
    open(trace_path, "w").close()
    invariants.install(trace_path)
    cluster = build_cluster(CONTROL_ON)
    ray_tpu.init(address=cluster.address, config=dict(CONTROL_ON))
    try:
        out["wedge"] = run_wedge_phase(deadline_s=20.0)
        print("wedge rescue:", json.dumps(out["wedge"]), flush=True)
        out["defense_on"] = run_arm(n_waves, warmup_waves, slo_s)
        print("defense ON:", json.dumps(out["defense_on"]), flush=True)
        nodes = ray_tpu.nodes()
        out["on_quarantined"] = sorted(
            n["NodeID"] for n in nodes if n.get("Quarantined"))
        out["on_health"] = {n["NodeID"]: n.get("Health", "OK")
                            for n in nodes}
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        invariants.uninstall()
    violations = invariants.check_trace(trace_path, strict_terminal=True)
    out["invariant_violations"] = [v.format() for v in violations]
    out.update(_trace_spec_stats(trace_path))
    print(f"protocol trace: {trace_path} ({len(violations)} violations, "
          "strict-terminal incl. speculation conservation)", flush=True)
    for v in violations:
        print("  " + v.format(), flush=True)

    # ---- arm B: defense OFF, same chaos spec, same waves
    cluster = build_cluster(CONTROL_OFF)
    ray_tpu.init(address=cluster.address, config=dict(CONTROL_OFF))
    try:
        out["defense_off"] = run_arm(n_waves, warmup_waves, slo_s)
        print("defense OFF:", json.dumps(out["defense_off"]), flush=True)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        chaos.uninstall()
        os.environ.pop("RAY_TPU_CHAOS_SPEC", None)

    on, off = out["defense_on"], out["defense_off"]
    out["p99_ratio_off_on"] = round(
        off["p99_s"] / max(on["p99_s"], 1e-9), 2)
    out["goodput_ratio_on_off"] = round(
        on["goodput_rps"] / max(off["goodput_rps"], 1e-9), 2)
    out["gates"] = {
        "p99_bar": p99_bar,
        "goodput_bar": goodput_bar,
        "p99_ok": out["p99_ratio_off_on"] >= p99_bar,
        "goodput_ok": out["goodput_ratio_on_off"] >= goodput_bar,
        "all_resolved":
            on["silently_unresolved"] == 0
            and off["silently_unresolved"] == 0
            and on["resolved"] == on["submitted"]
            and off["resolved"] == off["submitted"],
        "wedge_rescued":
            out["wedge"]["unresolved"] == 0
            and out["speculative_launches"] >= 1,
        "quarantine_engaged": bool(out["on_quarantined"]),
        "invariants_clean": not out["invariant_violations"],
    }
    out["storm_pass"] = all(out["gates"].values())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--waves", type=int, default=28)
    ap.add_argument("--warmup-waves", type=int, default=6)
    ap.add_argument("--slow-factor", type=float, default=25.0)
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: fewer waves, same 25x slow rule and "
                         "the same zero-unresolved + invariant teeth")
    ap.add_argument("--json", default=None, metavar="FILE")
    args = ap.parse_args(argv)

    if args.smoke:
        rec = run_storm(seed=args.seed, n_waves=14, warmup_waves=5,
                        slow_factor=args.slow_factor, p99_bar=3.0,
                        goodput_bar=2.0)
    else:
        rec = run_storm(seed=args.seed, n_waves=args.waves,
                        warmup_waves=args.warmup_waves,
                        slow_factor=args.slow_factor)
    print("gray storm:", json.dumps(rec), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rec, f, indent=2)
        print("record ->", args.json, flush=True)
    print("GRAY STORM:", "GREEN" if rec["storm_pass"] else "RED",
          flush=True)
    return 0 if rec["storm_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
