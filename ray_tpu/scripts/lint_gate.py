"""One-command CI / pre-commit gate for the analysis toolchain.

    python -m ray_tpu.scripts.lint_gate [--tier1] [--artifact-dir DIR]

Runs, in order, failing fast with a distinct exit code per contract:

1. the FULL linter (every registered checker) over ``ray_tpu/`` with the
   committed ratchet baseline — exit-code contract: 0 clean, 1 new
   findings, 2 usage/parse errors;
2. the baseline-ratchet check: the committed baseline must be EMPTY
   (violations get fixed or pragma'd, never grandfathered — entries may
   only ever be removed);
3. a ``--dump-protocol`` extraction (the protocol model must stay
   parseable) cross-checked against the invariant checker's METHOD_TABLE
   — every rpc method the dynamic half models must exist statically;
4. optionally (``--explore``) a budgeted run of the deterministic
   control-plane model checker (analysis/explore.py) over the full
   scenario library — wall-capped per scenario for the 2-CPU CI box;
   any invariant violation on any explored interleaving fails the gate
   (artifact: ``explore.json`` with per-scenario schedule counts and
   handler-pair coverage);
4b. optionally (``--memmodel``) the word-level seqlock-channel model
   checker (analysis/memmodel.py): the op-sequence round-trip gate
   against ``dag/channel.py``, a wall-capped exploration of the channel
   scenario library (kill-at-any-op included), and the seeded-bug
   regression — both ``channel.SEEDED_BUGS`` must be found and shrink
   to <= 12-op replays (artifact: ``memmodel.json``; counterexamples
   land as ``memmodel_replay.json``);
4b2. optionally (``--race``) the hybrid happens-before race sanitizer
   (analysis/racer.py): the watchlist round-trip (every STATIC
   watchlist entry must resolve dynamically — static watchlist ⊆
   instrumented set), the CLEAN probes (any race found in the live
   tree fails the gate — fixed, never suppressed, same EMPTY-baseline
   rule as the linter), and the seeded-bug regression — both
   ``SEEDED_RACES`` (the re-introduced node_daemon PR 6 fix and the
   alias-laundered fastpath lock) must be detected within <= 2
   quiescence rounds with a two-stack report (artifact: ``race.json``);
4b2b. optionally (``--waitgraph``) the wait-graph liveness gate
   (analysis/waitgraph.py): the static blocking graph over the control
   plane must be cycle-free, the pragma-stripped seeded modules must
   still fire ``blocking-wait-under-lock`` (the static tooth), the
   clean live probes must report no deadlock (live findings get fixed,
   never baselined), and both ``SEEDED_WAITS`` teeth must be detected
   dynamically within <= 2 probe rounds with a two-stack report (the
   GCS tooth additionally carrying the RPC chain) — artifact:
   ``waitgraph.json``;
4b3. optionally (``--rpc-budget``) the per-operation RPC budget ratchet
   (analysis/rpcflow.py): the interprocedural cost table must build with
   no unresolved entries, the committed ``.rpc-budget.json`` must pass
   the ratchet rules (zero-ops pinned at 0, >= 8 budgeted ops), and a
   live re-measurement on an embedded cluster must fit BOTH the
   committed budget and the statically-predicted multiplicity class per
   op (artifact: ``rpc_budget.json``);
4c. optionally (``--serve-storm``) the serve fast-path chaos storm in
   smoke mode (scripts/serve_storm.py): closed-loop traffic under seeded
   replica/node kills, gated on zero lost / duplicate / wrong responses
   (artifact: ``serve_storm.json``);
5. optionally (``--tier1``) the tier-1 pytest run with ``--durations=25``,
   teeing output to an artifact file so CI keeps a per-test timing
   budget trail (see BENCH_NOTES.md "Tier-1 wall-cap hygiene").

Artifacts land in ``--artifact-dir`` (default ``artifacts/``):
``lint.json`` (machine-readable findings), ``protocol.json`` (the dumped
model), ``memmodel.json`` (when --memmodel ran), ``rpc_budget.json``
(when --rpc-budget ran), ``tier1_durations.txt`` (when --tier1 ran).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
BASELINE = os.path.join(REPO, ".ray-lint-baseline.json")

TIER1_CMD = (
    "set -o pipefail; timeout -k 10 870 env JAX_PLATFORMS=cpu "
    "python -m pytest tests/ -q -m 'not slow' --durations=25 "
    "--continue-on-collection-errors -p no:cacheprovider"
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--explore", action="store_true",
                    help="also run a budgeted exploration of the full "
                         "model-checker scenario library; nonzero exit "
                         "on any violated interleaving")
    ap.add_argument("--explore-budget", type=int, default=1400,
                    help="DFS schedules per scenario (default 1400)")
    ap.add_argument("--explore-samples", type=int, default=800,
                    help="random schedules per scenario (default 800)")
    ap.add_argument("--explore-wall-cap", type=float, default=60.0,
                    help="seconds per scenario (default 60, sized for "
                         "the 2-CPU box)")
    ap.add_argument("--memmodel", action="store_true",
                    help="also run the word-level channel model checker "
                         "(analysis/memmodel.py): op-sequence round-trip "
                         "gate, full scenario library (kill-at-any-op), "
                         "and the seeded-bug regression (both bugs must "
                         "be found and shrink to <= 12 ops)")
    ap.add_argument("--memmodel-budget", type=int, default=1000,
                    help="DFS schedules per channel scenario "
                         "(default 1000)")
    ap.add_argument("--memmodel-samples", type=int, default=300,
                    help="random schedules per channel scenario "
                         "(default 300)")
    ap.add_argument("--memmodel-wall-cap", type=float, default=30.0,
                    help="seconds per channel scenario (default 30)")
    ap.add_argument("--race", action="store_true",
                    help="also run the happens-before race sanitizer "
                         "gate (analysis/racer.py): watchlist "
                         "round-trip, clean probes (any live race "
                         "fails), and the seeded-bug detection bar "
                         "(<= 2 quiescence rounds, two-stack report); "
                         "artifact: race.json")
    ap.add_argument("--race-rounds", type=int, default=2,
                    help="seeded-bug detection bar in quiescence "
                         "rounds (default 2; detection is "
                         "deterministic in round 1)")
    ap.add_argument("--waitgraph", action="store_true",
                    help="also run the wait-graph liveness gate "
                         "(analysis/waitgraph.py): static blocking-"
                         "cycle scan, the pragma-stripped seeded-tooth "
                         "bar, clean live deadlock probes, and the "
                         "seeded dynamic detection bar (<= 2 rounds, "
                         "two-stack report + rpc chain); artifact: "
                         "waitgraph.json")
    ap.add_argument("--waitgraph-rounds", type=int, default=2,
                    help="seeded wait-bug detection bar in probe "
                         "rounds (default 2; detection is "
                         "deterministic in round 1)")
    ap.add_argument("--rpc-budget", action="store_true",
                    help="also run the per-operation RPC budget ratchet "
                         "(analysis/rpcflow.py): static cost table, "
                         "committed-budget ratchet rules, and a live "
                         "re-measurement on an embedded cluster gated "
                         "on budget AND predicted multiplicity class; "
                         "artifact: rpc_budget.json")
    ap.add_argument("--rpc-budget-iters", type=int, default=12,
                    help="measured iterations per driver operation "
                         "(default 12; a warmup pass always precedes "
                         "the measured pass)")
    ap.add_argument("--serve-storm", action="store_true",
                    help="also run the serve fast-path chaos storm in "
                         "SMOKE mode (scripts/serve_storm.py --smoke): "
                         "short closed-loop phases under seeded replica/"
                         "node kills with the SLO gate (zero lost / "
                         "duplicate / wrong responses) wired into the "
                         "exit code; artifact: serve_storm.json")
    ap.add_argument("--overload-storm", action="store_true",
                    help="also run the overload-control storm in SMOKE "
                         "mode (scripts/overload_storm.py --smoke): "
                         "bursty open-loop traffic past saturation with "
                         "the control-plane A/B, gated on zero silent "
                         "drops, the goodput ratio/fraction bars, and a "
                         "clean strict-terminal invariant check "
                         "(artifact: overload_storm.json)")
    ap.add_argument("--gray-storm", action="store_true",
                    help="also run the gray-failure defense storm in "
                         "SMOKE mode (scripts/gray_storm.py --smoke): "
                         "2-of-5 nodes chaos-slowed 25x, A/B over the "
                         "defense plane, gated on the p99/goodput "
                         "recovery bars, the wedged-gang speculation "
                         "rescue, quarantine engagement, and a clean "
                         "strict-terminal invariant check "
                         "(artifact: gray_storm.json)")
    ap.add_argument("--tier1", action="store_true",
                    help="also run the tier-1 suite with --durations=25 "
                         "and save the output as an artifact")
    ap.add_argument("--artifact-dir", default=os.path.join(REPO, "artifacts"))
    args = ap.parse_args(argv)
    os.makedirs(args.artifact_dir, exist_ok=True)

    # (1) full linter, all checkers, ratchet baseline, JSON out
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", "ray_tpu",
         "--baseline", BASELINE, "--format", "json"],
        cwd=REPO, capture_output=True, text=True,
    )
    lint_path = os.path.join(args.artifact_dir, "lint.json")
    with open(lint_path, "w") as f:
        f.write(proc.stdout)
    if proc.returncode == 2:
        print("lint_gate: analysis CLI usage/parse error", file=sys.stderr)
        sys.stderr.write(proc.stderr)
        return 2
    try:
        lint = json.loads(proc.stdout)
    except json.JSONDecodeError:
        print("lint_gate: analysis CLI emitted unparseable JSON",
              file=sys.stderr)
        return 2
    if proc.returncode == 1 or lint["new"]:
        print(f"lint_gate: {len(lint['new'])} NEW finding(s) — fix or "
              "pragma them (the baseline only ratchets down):",
              file=sys.stderr)
        for fnd in lint["new"]:
            print(f"  {fnd['path']}:{fnd['line']}: [{fnd['check']}] "
                  f"{fnd['message']}", file=sys.stderr)
        return 1
    print(f"lint: clean ({lint['files_scanned']} files, "
          f"{len(lint['checks'])} checkers, {lint['suppressed']} "
          "pragma-suppressed)")

    # (2) baseline ratchet: committed baseline stays EMPTY
    if os.path.exists(BASELINE):
        with open(BASELINE) as f:
            entries = json.load(f).get("findings", {})
        if entries:
            print(f"lint_gate: committed baseline carries {len(entries)} "
                  "entries — it must stay empty (fix, don't grandfather)",
                  file=sys.stderr)
            return 1
    print("baseline: empty (ratchet holds)")

    # (3) protocol model extraction + dynamic/static cross-check
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.analysis", "ray_tpu",
         "--dump-protocol"],
        cwd=REPO, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print("lint_gate: --dump-protocol failed", file=sys.stderr)
        sys.stderr.write(proc.stderr)
        return 2
    with open(os.path.join(args.artifact_dir, "protocol.json"), "w") as f:
        f.write(proc.stdout)
    model = json.loads(proc.stdout)
    from ray_tpu.analysis.invariants import METHOD_TABLE

    missing = sorted(set(METHOD_TABLE) - set(model["handlers"]))
    if missing:
        print("lint_gate: invariant METHOD_TABLE names rpc methods with "
              f"no static handler: {missing}", file=sys.stderr)
        return 1
    print(f"protocol: {len(model['handlers'])} methods, "
          f"{len(model['calls'])} call sites; invariant method table "
          "round-trips")

    # (4) budgeted interleaving exploration of the scenario library
    if args.explore:
        from ray_tpu.analysis import explore as _explore

        report = {}
        failed = None
        total = 0
        for name in sorted(_explore.SCENARIOS):
            res = _explore.explore(
                _explore.SCENARIOS[name],
                max_schedules=args.explore_budget,
                samples=args.explore_samples,
                wall_cap_s=args.explore_wall_cap,
            )
            print("explore: " + res.summary())
            total += res.schedules_run
            report[name] = {
                "schedules": res.schedules_run,
                "pruned": res.branches_pruned,
                "coverage_pairs": len(res.coverage),
                "violations": [
                    v.format()
                    for v in (res.violating.violations if res.found else [])
                ],
                "shrunk": res.shrunk,
            }
            if res.found and failed is None:
                failed = name
                cex = os.path.join(args.artifact_dir, "explore_replay.json")
                _explore.write_replay(cex, res)
                print(f"lint_gate: counterexample replay: {cex} "
                      "(python -m ray_tpu.analysis --replay)",
                      file=sys.stderr)
        with open(os.path.join(args.artifact_dir, "explore.json"),
                  "w") as f:
            json.dump(report, f, indent=2)
        if failed is not None:
            print(f"lint_gate: scenario {failed} has a violated "
                  "interleaving", file=sys.stderr)
            return 1
        print(f"explore: {total} schedules across "
              f"{len(report)} scenarios, 0 violations")

    # (4b) word-level channel model checker: static round-trip gate +
    # exhaustive-ish interleaving run + seeded-bug regression teeth
    if args.memmodel:
        from ray_tpu.analysis import memmodel as _memmodel

        failed = False
        report = {"round_trip": [], "scenarios": {}, "seeded": {}}
        report["round_trip"] = _memmodel.verify_op_sequences()
        for msg in report["round_trip"]:
            print(f"lint_gate: memmodel round-trip: {msg}",
                  file=sys.stderr)
            failed = True
        if not report["round_trip"]:
            print("memmodel: op-sequence round-trip holds "
                  "(write/read/close/poke_error vs DECLARED_SEQUENCES)")
        total = 0
        for name in sorted(_memmodel.CHANNEL_SCENARIOS):
            res = _memmodel.explore_channel(
                _memmodel.CHANNEL_SCENARIOS[name],
                max_schedules=args.memmodel_budget,
                samples=args.memmodel_samples,
                wall_cap_s=args.memmodel_wall_cap,
            )
            print("memmodel: " + res.summary())
            total += res.schedules_run
            report["scenarios"][name] = {
                "schedules": res.schedules_run,
                "pruned": res.branches_pruned,
                "ops": res.ops_covered,
                "crash_points": len(res.crash_points),
                "violations": [
                    v.format()
                    for v in (res.violating.violations if res.found else [])
                ],
                "shrunk": res.shrunk,
            }
            if res.found:
                failed = True
                cex = os.path.join(args.artifact_dir,
                                   "memmodel_replay.json")
                _memmodel.write_channel_replay(cex, res)
                print(f"lint_gate: channel counterexample replay: {cex} "
                      "(python -m ray_tpu.analysis --replay)",
                      file=sys.stderr)
        # regression teeth: each seeded bug must be FOUND and shrink small
        for bug, scen in _memmodel.SEEDED_BUG_SCENARIOS:
            res = _memmodel.explore_channel(
                _memmodel.CHANNEL_SCENARIOS[scen],
                max_schedules=args.memmodel_budget,
                samples=args.memmodel_samples,
                seeded_bugs=[bug],
                wall_cap_s=args.memmodel_wall_cap,
            )
            found = res.found and len(res.shrunk or ()) <= 12
            report["seeded"][bug] = {
                "scenario": scen,
                "found": res.found,
                "shrunk_ops": len(res.shrunk or ()) if res.found else None,
            }
            if not found:
                failed = True
                print(f"lint_gate: seeded channel bug {bug!r} "
                      + ("shrank to "
                         f"{len(res.shrunk or res.violating.schedule)} "
                         "ops (> 12)" if res.found else "NOT FOUND")
                      + " — the checker lost its teeth", file=sys.stderr)
            else:
                print(f"memmodel: seeded bug {bug} found in {scen}, "
                      f"shrunk to {len(res.shrunk)} ops")
        with open(os.path.join(args.artifact_dir, "memmodel.json"),
                  "w") as f:
            json.dump(report, f, indent=2)
        if failed:
            print("lint_gate: channel memory model gate failed",
                  file=sys.stderr)
            return 1
        print(f"memmodel: {total} schedules across "
              f"{len(report['scenarios'])} scenarios, 0 violations")

    # (4b2) happens-before race sanitizer: watchlist round-trip, clean
    # probes (EMPTY baseline: live races get fixed, never suppressed),
    # and the seeded-bug regression teeth
    if args.race:
        from ray_tpu.analysis import racer as _racer

        failed = False
        report = {"watchlist": {}, "probes": {}, "seeded": {}}
        wl = _racer.extract_watchlist()
        probe = _racer.RaceSanitizer(watchlist=wl)
        probe.install()
        probe.uninstall()
        report["watchlist"] = {
            "entries": len(wl),
            "classes": sorted({e["cls"] for e in wl}),
            "unresolved": [
                {"entry": e, "error": err} for e, err in probe.unresolved
            ],
        }
        if probe.unresolved:
            failed = True
            for e, err in probe.unresolved:
                print("lint_gate: watchlist entry "
                      f"{e['cls']}.{e['field']} did not resolve "
                      f"dynamically: {err} (static watchlist must be a "
                      "subset of the instrumented set)", file=sys.stderr)
        else:
            print(f"race: watchlist round-trips ({len(wl)} entries, "
                  f"{len(report['watchlist']['classes'])} classes, all "
                  "instrumented)")
        for name in sorted(_racer.RACE_PROBES):
            res = _racer.run_probe(name, rounds=args.race_rounds,
                                   watchlist=wl)
            report["probes"][name] = {
                "rounds": res.rounds,
                "races": res.races,
            }
            if res.detected:
                failed = True
                print(f"lint_gate: race probe {name} found a LIVE race "
                      "— fix it (the baseline stays empty):",
                      file=sys.stderr)
                for r in res.races:
                    print(f"  {r['kind']} on {r['field']}",
                          file=sys.stderr)
            else:
                print(f"race: probe {name} clean "
                      f"({res.rounds} round(s))")
        for bug, _mod, pname in _racer.SEEDED_RACES:
            res = _racer.run_probe(pname, seeded_bugs=[bug],
                                   rounds=args.race_rounds, watchlist=wl)
            two_stack = bool(
                res.races
                and res.races[0]["prior"].get("stack")
                and res.races[0]["current"].get("stack")
            )
            ok = res.detected and res.rounds <= args.race_rounds \
                and two_stack
            report["seeded"][bug] = {
                "probe": pname,
                "detected": res.detected,
                "rounds": res.rounds,
                "two_stack": two_stack,
                "static_claim_violated": bool(
                    res.races and res.races[0]["static_claim_violated"]
                ),
            }
            if not ok:
                failed = True
                print(f"lint_gate: seeded race {bug!r} "
                      + (f"took {res.rounds} rounds (> "
                         f"{args.race_rounds})" if res.detected
                         else "NOT DETECTED")
                      + " — the racer lost its teeth", file=sys.stderr)
            else:
                claim = report["seeded"][bug]["static_claim_violated"]
                print(f"race: seeded bug {bug} detected in "
                      f"{res.rounds} round(s), two-stack report"
                      + (", static claim flagged" if claim else ""))
        with open(os.path.join(args.artifact_dir, "race.json"),
                  "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        if failed:
            print("lint_gate: race sanitizer gate failed",
                  file=sys.stderr)
            return 1

    # (4b2b) wait-graph liveness gate: the static blocking graph must be
    # cycle-free, the pragma-stripped seeded modules must still fire
    # blocking-wait-under-lock (the static tooth), the clean probes must
    # find no live deadlock (EMPTY-baseline rule), and both seeded
    # teeth must be caught dynamically with a two-stack report
    if args.waitgraph:
        import re as _re
        import shutil as _shutil
        import tempfile as _tempfile

        from ray_tpu.analysis.core import analyze_paths as _analyze
        from ray_tpu.analysis import waitgraph as _wg

        failed = False
        report = {"static": {}, "seeded_static": {}, "probes": {},
                  "seeded": {}}

        wg_report = _wg.build_waitgraph(root=REPO)
        report["static"] = {
            "contexts": len(wg_report.contexts),
            "edges": len(wg_report.edges),
            "cycles": [list(c) for c in wg_report.cycles],
        }
        if wg_report.cycles:
            failed = True
            for c in wg_report.cycles:
                print("lint_gate: static blocking cycle: "
                      + " -> ".join(c + [c[0]]), file=sys.stderr)
        else:
            print(f"waitgraph: static blocking graph cycle-free "
                  f"({len(wg_report.contexts)} contexts, "
                  f"{len(wg_report.edges)} rpc edges)")

        # seeded static bar: strip every ray-lint pragma off the two
        # seeded modules and rescan — blocking-wait-under-lock must
        # fire in each, or the static half lost its teeth
        seeded_mods = ("ray_tpu/cluster/gcs.py", "ray_tpu/dag/compiled.py")
        pragma_re = _re.compile(r"#\s*ray-lint:[^\n]*")
        tmp = _tempfile.mkdtemp(prefix="wg-gate-")
        try:
            for rel in seeded_mods:
                dst = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                with open(os.path.join(REPO, rel)) as f:
                    stripped = pragma_re.sub("", f.read())
                with open(dst, "w") as f:
                    f.write(stripped)
            res = _analyze([os.path.join(tmp, "ray_tpu")], root=tmp,
                           select=["blocking-wait-under-lock"])
            fired = {rel: sum(1 for f_ in res.findings if f_.path == rel)
                     for rel in seeded_mods}
            report["seeded_static"] = fired
            for rel, n in fired.items():
                if not n:
                    failed = True
                    print(f"lint_gate: pragma-stripped {rel} raised NO "
                          "blocking-wait-under-lock finding — the "
                          "static tooth is gone", file=sys.stderr)
            if all(fired.values()):
                print("waitgraph: pragma-stripped seeded modules fire "
                      "blocking-wait-under-lock ("
                      + ", ".join(f"{rel}: {n}"
                                  for rel, n in sorted(fired.items()))
                      + ")")
        finally:
            _shutil.rmtree(tmp, ignore_errors=True)

        for name in sorted(_wg.WAIT_PROBES):
            res = _wg.run_probe(name, rounds=args.waitgraph_rounds)
            report["probes"][name] = {
                "rounds": res.rounds,
                "deadlocks": res.deadlocks,
                "stalls": len(res.stalls),
            }
            if res.detected:
                failed = True
                print(f"lint_gate: wait probe {name} found a LIVE "
                      "deadlock — fix it (the baseline stays empty)",
                      file=sys.stderr)
            else:
                print(f"waitgraph: probe {name} clean "
                      f"({res.rounds} round(s))")
        for bug, _mod, pname in _wg.SEEDED_WAITS:
            res = _wg.run_probe(pname, seeded_bugs=[bug],
                                rounds=args.waitgraph_rounds)
            rep0 = res.deadlocks[0] if res.deadlocks else {}
            threads = rep0.get("threads", ())
            two_stack = sum(1 for t in threads if t.get("stack")) >= 2
            # a cycle through an rpc-srv resource must carry the
            # Lamport-stitched chain of in-flight calls; pure
            # lock/channel cycles have no rpc hop to report
            needs_chain = any("rpc" in str(t.get("waiting_on", ""))
                              for t in threads)
            chain_ok = (not needs_chain) or bool(rep0.get("rpc_chain"))
            ok = (res.detected and res.rounds <= args.waitgraph_rounds
                  and two_stack and chain_ok)
            report["seeded"][bug] = {
                "probe": pname,
                "detected": res.detected,
                "rounds": res.rounds,
                "two_stack": two_stack,
                "rpc_chain": len(rep0.get("rpc_chain") or ()),
            }
            if not ok:
                failed = True
                print(f"lint_gate: seeded wait bug {bug!r} "
                      + (f"took {res.rounds} rounds (> "
                         f"{args.waitgraph_rounds}) or lost the "
                         "two-stack/rpc-chain report" if res.detected
                         else "NOT DETECTED")
                      + " — the sanitizer lost its teeth",
                      file=sys.stderr)
            else:
                print(f"waitgraph: seeded bug {bug} detected in "
                      f"{res.rounds} round(s), two-stack report, "
                      f"{report['seeded'][bug]['rpc_chain']} rpc hop(s)")
        with open(os.path.join(args.artifact_dir, "waitgraph.json"),
                  "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        if failed:
            print("lint_gate: wait-graph gate failed", file=sys.stderr)
            return 1

    # (4b3) per-operation RPC budget ratchet: static cost table ->
    # committed budget rules -> live re-measurement (the honesty gate:
    # measured frames must fit the budget AND the predicted class)
    if args.rpc_budget:
        from ray_tpu.analysis import rpcflow as _rpcflow

        failed = False
        budget_path = os.path.join(REPO, _rpcflow.DEFAULT_BUDGET_FILE)
        report = _rpcflow.build_rpcflow(["ray_tpu"], root=REPO)
        art = {"ops": {op: c.to_dict() for op, c in report.ops.items()}}
        if report.unresolved_entries:
            failed = True
            for op, why in report.unresolved_entries:
                print(f"lint_gate: rpcflow entry point {op} unresolved: "
                      f"{why}", file=sys.stderr)
        try:
            budget = _rpcflow.load_budget(budget_path)
        except (OSError, ValueError) as e:
            print(f"lint_gate: cannot load committed RPC budget: {e}",
                  file=sys.stderr)
            return 1
        art["budget"] = budget
        errs = _rpcflow.ratchet_check(budget, budget)
        if len(budget) < 8:
            errs.append(f"budget table has {len(budget)} ops, need >= 8")
        for e in errs:
            failed = True
            print(f"lint_gate: rpc budget: {e}", file=sys.stderr)
        if not failed:
            print(f"rpc-budget: static table ok "
                  f"({len(report.ops)} ops over "
                  f"{report.functions_indexed} functions), committed "
                  f"budget ok ({len(budget)} ops, "
                  f"{', '.join(_rpcflow.ZERO_STEADY_STATE_OPS)} at 0)")
        measured = None
        if not failed:
            res = _rpcflow.measure_rpc_budget(
                iters=args.rpc_budget_iters)
            measured = res["per_op"]
            art["measured"] = measured
            art["profile"] = res["snapshot"]
            for e in _rpcflow.check_measured(measured, budget, report):
                failed = True
                print(f"lint_gate: rpc budget: {e}", file=sys.stderr)
        with open(os.path.join(args.artifact_dir, "rpc_budget.json"),
                  "w") as f:
            json.dump(art, f, indent=2, sort_keys=True)
        if failed:
            print("lint_gate: rpc budget gate failed", file=sys.stderr)
            return 1
        print("rpc-budget: measured frames fit the committed budget "
              "and the predicted classes:")
        print("  " + _rpcflow.budget_table(measured, report)
              .replace("\n", "\n  "))

    # (4c) serve fast-path chaos-storm smoke: the SLO gate (zero lost /
    # duplicate / wrong responses under seeded kills) as a CI check
    if args.serve_storm:
        art = os.path.join(args.artifact_dir, "serve_storm.json")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.serve_storm",
             "--smoke", "--json", art],
            cwd=REPO, capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            print("lint_gate: serve storm SLO gate RED", file=sys.stderr)
            sys.stderr.write(proc.stderr[-2000:])
            return 1
        print(f"serve_storm: SLO green (artifact: {art})")

    # (4d) overload-control storm smoke: the graceful-degradation gate
    # (no silent drops, goodput holds vs the control-off collapse arm)
    if args.overload_storm:
        art = os.path.join(args.artifact_dir, "overload_storm.json")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.overload_storm",
             "--smoke", "--json", art],
            cwd=REPO, capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            print("lint_gate: overload storm gate RED (silent drop or "
                  "goodput collapse)", file=sys.stderr)
            sys.stderr.write(proc.stderr[-2000:])
            return 1
        print(f"overload_storm: gate green (artifact: {art})")

    # (4e) gray-failure defense storm smoke: the tail-latency-recovery
    # gate (quarantine engages, speculation rescues the wedged gang,
    # zero duplicate task_done applies in the strict-terminal trace)
    if args.gray_storm:
        art = os.path.join(args.artifact_dir, "gray_storm.json")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.gray_storm",
             "--smoke", "--json", art],
            cwd=REPO, capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            print("lint_gate: gray storm gate RED (tail latency not "
                  "recovered, wedge not rescued, or invariant "
                  "violation)", file=sys.stderr)
            sys.stderr.write(proc.stderr[-2000:])
            return 1
        print(f"gray_storm: gate green (artifact: {art})")

    # (5) tier-1 with per-test durations as a CI artifact. The pytest
    # process writes a final metrics snapshot at exit (util/metrics.py
    # RAY_TPU_METRICS_DUMP hook) so control-plane regressions — handler
    # latency shifts, retry storms — are diffable across CI runs.
    if args.tier1:
        art = os.path.join(args.artifact_dir, "tier1_durations.txt")
        metrics_art = os.path.join(args.artifact_dir, "tier1_metrics.prom")
        env = dict(os.environ, RAY_TPU_METRICS_DUMP=metrics_art)
        with open(art, "w") as f:
            proc = subprocess.Popen(
                ["bash", "-c", TIER1_CMD], cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for line in proc.stdout:
                sys.stdout.write(line)
                f.write(line)
            rc = proc.wait()
        print(f"tier-1 durations artifact: {art}")
        if os.path.exists(metrics_art):
            with open(metrics_art) as f:
                lines = f.read().splitlines()
            series = [ln for ln in lines
                      if ln and not ln.startswith("#")]
            print(f"tier-1 metrics snapshot: {metrics_art} "
                  f"({len(series)} series); handler totals:")
            for ln in series:
                if "_rpc_handler_s_count" in ln or ln.startswith(
                        ("ray_tpu_rpc_reconnects", "ray_tpu_rpc_resends")):
                    print("  " + ln)
        if rc != 0:
            print(f"lint_gate: tier-1 run failed (rc={rc})", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
