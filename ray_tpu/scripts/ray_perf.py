"""Single-node microbenchmark (reference: python/ray/_private/ray_perf.py,
the `ray microbenchmark` CLI): tasks/s, actor calls/s, put/get throughput.
These are the canonical quick numbers SURVEY §6 tracks."""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


def run_microbenchmark(address=None, quick: bool = False) -> Dict[str, float]:
    import ray_tpu

    ray_tpu.init(address=address, ignore_reinit_error=True)
    results: Dict[str, float] = {}

    @ray_tpu.remote
    def noop():
        return None

    # warmup
    ray_tpu.get([noop.remote() for _ in range(10)])

    n_tasks = 200 if quick else 2000
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(n_tasks)])
    dt = time.perf_counter() - t0
    results["tasks_per_second"] = n_tasks / dt

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())  # warmup/creation
    n_calls = 500 if quick else 5000
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n_calls)])
    dt = time.perf_counter() - t0
    results["actor_calls_per_second"] = n_calls / dt

    mb = 8 if quick else 64
    arr = np.zeros(mb * 1024 * 1024 // 8, dtype=np.float64)
    t0 = time.perf_counter()
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    dt = time.perf_counter() - t0
    # honest labels: in local mode put/get is a MemoryStore dict round-trip
    # (no serialization, no shm) — a cache-speed number, not data-plane
    # bandwidth. Cluster mode measures the real pack->shm->unpack path.
    key = "put_get_gbps_shm" if address else "put_get_gbps_memstore"
    results[key] = (arr.nbytes * 2 / dt) / 1e9
    assert out.nbytes == arr.nbytes

    if address:
        # worker-side zero-copy consumption: a task reading a large shm
        # object through the pinned-view path
        @ray_tpu.remote
        def consume(a):
            return a.nbytes

        t0 = time.perf_counter()
        nbytes = ray_tpu.get(consume.remote(ref))
        dt = time.perf_counter() - t0
        results["arg_view_gbps"] = (nbytes / dt) / 1e9

    return results


def main(address=None, quick=False):
    results = run_microbenchmark(address, quick)
    print(f"{'benchmark':<28}{'value':>14}")
    for k, v in results.items():
        print(f"{k:<28}{v:>14.1f}")
    return results


if __name__ == "__main__":
    main()
