"""GCS-restart soak: tasks/actors flowing while the control plane restarts.

Run as: python -m ray_tpu.scripts.gcs_soak [seconds]. Every task result
is value-checked; "errors" must stay 0 across restarts (snapshot persist
-> kill -> same-port restart -> daemon/driver reconnect + resubmit).
Last recorded run (2026-07-30, 1-core host): 420s, 302 GCS restarts,
32,027 tasks, 9,680 named-actor calls, 0 errors.
"""
import random, sys, time
import ray_tpu
from ray_tpu.cluster.cluster_utils import Cluster

DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
random.seed(11)
persist = "/tmp/gcs_soak_tables.pkl"
cluster = Cluster(persistence_path=persist)
cluster.add_node(num_cpus=2)
cluster.add_node(num_cpus=2)
ray_tpu.init(address=cluster.address)

@ray_tpu.remote(max_retries=10)
def work(i):
    time.sleep(random.random() * 0.05)
    return i * 3

@ray_tpu.remote(max_restarts=-1)
class Keeper:
    def __init__(self): self.n = 0
    def bump(self): self.n += 1; return self.n

k = Keeper.options(name="keeper").remote()
stats = {"tasks": 0, "errors": 0, "restarts": 0, "actor_ok": 0, "actor_err": 0}
t_end = time.time() + DURATION
pending = []
i = 0
last = time.time()
while time.time() < t_end:
    i += 1
    pending.append((i, work.remote(i)))
    if random.random() < 0.3:
        try:
            ray_tpu.get(k.bump.remote(), timeout=30)
            stats["actor_ok"] += 1
        except Exception:
            stats["actor_err"] += 1
    if random.random() < 0.01:
        cluster.gcs._persist_now()
        cluster.restart_gcs()
        stats["restarts"] += 1
        time.sleep(0.5)
    while len(pending) > 40:
        j, ref = pending.pop(0)
        try:
            assert ray_tpu.get(ref, timeout=60) == j * 3
            stats["tasks"] += 1
        except Exception as e:
            stats["errors"] += 1
            print("ERR:", repr(e)[:150], flush=True)
    if time.time() - last > 30:
        print("t=%.0f %s" % (DURATION - (t_end - time.time()), stats), flush=True)
        last = time.time()
for j, ref in pending:
    try:
        assert ray_tpu.get(ref, timeout=90) == j * 3
        stats["tasks"] += 1
    except Exception as e:
        stats["errors"] += 1
        print("ERR-final:", repr(e)[:150], flush=True)
print("FINAL:", stats, flush=True)
ray_tpu.shutdown(); cluster.shutdown()
