"""CLI: start/stop/status/list/summary/timeline/memory/metrics/
microbenchmark.

Reference: python/ray/scripts/scripts.py (`ray start --head`,
`ray start --address`, `ray stop`, `ray status`, `ray list ...`,
`ray summary`, `ray timeline`, `ray memory`, `ray microbenchmark`) plus
the dashboard metrics view (`ray_tpu metrics` / `--top` / `--prom`, see
ray_tpu.obs). Invoke as ``python -m ray_tpu <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

_SESSION_DIR = "/tmp/ray_tpu"
_ADDR_FILE = os.path.join(_SESSION_DIR, "address")
_PID_FILE = os.path.join(_SESSION_DIR, "pids")


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or os.environ.get("RAY_TPU_ADDRESS")
    if not addr and os.path.exists(_ADDR_FILE):
        addr = open(_ADDR_FILE).read().strip()
    if not addr:
        sys.exit("no cluster address (use --address, RAY_TPU_ADDRESS, or "
                 "start a head node on this machine first)")
    return addr


def _record_pid(pid: int):
    os.makedirs(_SESSION_DIR, exist_ok=True)
    with open(_PID_FILE, "a") as f:
        f.write(f"{pid}\n")


def cmd_start(args):
    res = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        res["TPU"] = float(args.num_tpus)
    if args.memory:
        res["memory"] = float(args.memory)
    if args.resources:
        res.update(json.loads(args.resources))

    if not args.block:
        # daemonize: re-exec ourselves with --block in the background
        if args.head:
            # a stale address file from a crashed head would be mistaken for
            # the new head's address in the wait loop below
            try:
                os.remove(_ADDR_FILE)
            except OSError:
                pass
        cmd = [sys.executable, "-m", "ray_tpu"] + sys.argv[1:] + ["--block"]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        _record_pid(proc.pid)
        # wait for the address file (head) or just report (worker)
        if args.head:
            deadline = time.time() + 15
            while time.time() < deadline:
                if os.path.exists(_ADDR_FILE):
                    addr = open(_ADDR_FILE).read().strip()
                    print(f"ray_tpu head started at {addr} (pid {proc.pid})")
                    print(f"connect with: ray_tpu.init(address={addr!r})")
                    return
                time.sleep(0.1)
            sys.exit("head did not come up within 15s")
        print(f"ray_tpu node started (pid {proc.pid})")
        return

    # --block: run the node in THIS process
    from ray_tpu.analysis import waitgraph
    from ray_tpu.cluster.gcs import GcsServer
    from ray_tpu.cluster.node_daemon import NodeDaemon

    # `ray_tpu stacks` protocol: SIGUSR1 makes this process write an
    # annotated all-thread stack dump artifact (wait edges + held locks
    # when the wait sanitizer is live, plain stacks otherwise)
    waitgraph.install_stack_signal()
    if args.head:
        gcs = GcsServer(host="127.0.0.1", port=args.port or 0)
        addr = f"127.0.0.1:{gcs.port}"
        os.makedirs(_SESSION_DIR, exist_ok=True)
        with open(_ADDR_FILE, "w") as f:
            f.write(addr)
        daemon = NodeDaemon(("127.0.0.1", gcs.port), res, host="127.0.0.1")
        print(f"head up at {addr}")
    else:
        host, port = _resolve_address(args).rsplit(":", 1)
        daemon = NodeDaemon((host, int(port)), res, host="127.0.0.1")
        print(f"node joined {host}:{port}")
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.5)
    daemon.shutdown()
    if args.head:
        gcs.shutdown()
        try:
            os.remove(_ADDR_FILE)
        except OSError:
            pass


def cmd_stop(args):
    n = 0
    if os.path.exists(_PID_FILE):
        for line in open(_PID_FILE):
            try:
                os.kill(int(line.strip()), signal.SIGTERM)
                n += 1
            except (OSError, ValueError):
                pass
        os.remove(_PID_FILE)
    for f in (_ADDR_FILE,):
        try:
            os.remove(f)
        except OSError:
            pass
    print(f"stopped {n} process(es)")


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args), ignore_reinit_error=True)
    return ray_tpu


def cmd_stacks(args):
    """Dump all-thread stacks of every locally-started cluster process,
    annotated with the wait sanitizer's current wait edges and held
    locks (the observability face of the wait graph). Protocol: SIGUSR1
    each pid in the session pid file; each process's waitgraph signal
    handler writes a ``waitgraph-<pid>-stacks-*.jsonl`` artifact, which
    this command collects and pretty-prints."""
    from ray_tpu.analysis import waitgraph

    if not os.path.exists(_PID_FILE):
        sys.exit("no local session (start a node with `ray_tpu start` "
                 "first)")
    t0 = time.time()
    pids = []
    for line in open(_PID_FILE):
        try:
            pid = int(line.strip())
            os.kill(pid, signal.SIGUSR1)
            pids.append(pid)
        except (OSError, ValueError):
            pass
    if not pids:
        sys.exit("no live locally-started cluster process to signal")
    art_dir = args.artifact_dir or os.environ.get(
        "RAY_TPU_FLIGHTREC_DIR", "artifacts")
    found = {}
    deadline = t0 + args.timeout
    while time.time() < deadline and len(found) < len(pids):
        if os.path.isdir(art_dir):
            for name in sorted(os.listdir(art_dir)):
                if not name.startswith("waitgraph-") \
                        or "-stacks-" not in name:
                    continue
                try:
                    pid = int(name.split("-")[1])
                except (IndexError, ValueError):
                    continue
                path = os.path.join(art_dir, name)
                # only dumps written in RESPONSE to this signal round:
                # a stale artifact would report last week's stacks
                if pid in pids and pid not in found \
                        and os.path.getmtime(path) >= t0 - 1.0:
                    found[pid] = path
        time.sleep(0.1)
    if not found:
        sys.exit(f"signalled {len(pids)} process(es) but no stack dump "
                 f"appeared under {art_dir}/ within {args.timeout:.0f}s "
                 "(the node must run in the same working directory, or "
                 "set RAY_TPU_FLIGHTREC_DIR)")
    fmt = waitgraph.WaitSanitizer()  # formatting only, never installed
    for pid, path in sorted(found.items()):
        entries = []
        with open(path) as f:
            for ln in f:
                e = json.loads(ln)
                if e.get("kind") != "waitgraph-stacks":
                    entries.append(e)
        print(f"== pid {pid} — {len(entries)} thread(s) ({path})")
        print(fmt.format_stacks(entries))
    missing = sorted(set(pids) - set(found))
    if missing:
        sys.exit(f"no dump from pid(s) {missing}")


def cmd_status(args):
    rt = _connect(args)
    from ray_tpu.util import state

    s = state.summary()
    print("cluster summary:")
    for k, v in s.items():
        print(f"  {k:<20}{v}")
    print("resources:")
    total = rt.cluster_resources()
    avail = rt.available_resources()
    for k in sorted(total):
        print(f"  {k:<12}{avail.get(k, 0):>12.1f} / {total[k]:.1f}")


def cmd_list(args):
    _connect(args)
    from ray_tpu.util import state

    kind = args.kind
    fn = {
        "tasks": lambda: state.list_tasks(args.limit),
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "objects": lambda: state.list_objects(args.limit),
        "placement-groups": state.list_placement_groups,
        "cluster-events": lambda: state.list_cluster_events(args.limit),
    }[kind]
    rows = fn()
    print(json.dumps(rows, indent=1, default=str))


def cmd_summary(args):
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps(state.summarize_tasks(), indent=1))


def cmd_timeline(args):
    _connect(args)
    from ray_tpu.util.state import dump_timeline

    out = args.output or f"timeline_{int(time.time())}.json"
    dump_timeline(out)
    print(f"wrote chrome trace to {out} (open in chrome://tracing or Perfetto)")


def cmd_memory(args):
    _connect(args)
    from ray_tpu.util import state

    objs = state.list_objects(args.limit)
    total = sum(o.get("approx_size", 0) for o in objs)
    print(f"{len(objs)} objects, ~{total/1e6:.1f} MB (driver-visible)")
    for o in objs[:50]:
        print(f"  {o['object_id'][:16]:<18}{o['type']:<16}{o['approx_size']:>10}")


def cmd_microbenchmark(args):
    from ray_tpu.scripts.ray_perf import main as perf_main

    perf_main(address=getattr(args, "address", None), quick=args.quick)


def cmd_metrics(args):
    """Cluster-aggregated metrics view (ray_tpu.obs). Default: compact
    counter/gauge summary. ``--top``: rank GCS/daemon rpc-handler
    self-time — where the per-task control-plane milliseconds go.
    ``--prom``: raw Prometheus text (what the dashboard's /metrics
    serves)."""
    from ray_tpu.cluster.rpc import RpcClient

    host, _, port = _resolve_address(args).rpartition(":")
    c = RpcClient(host, int(port), name="cli-metrics", peer="gcs")
    try:
        if args.prom:
            print(c.call("metrics", {"format": "prometheus"},
                         timeout=15.0)["text"], end="")
            return
        agg = c.call("metrics", {"format": "json"}, timeout=15.0)["metrics"]
        if args.top:
            from ray_tpu.obs import rank_handler_time

            rows = rank_handler_time(agg, limit=args.limit)
            print(f"{'surface':<8}{'method':<28}{'node':<16}"
                  f"{'calls':>8}{'total_s':>10}{'mean_us':>10}")
            for r in rows:
                print(f"{r['surface']:<8}{r['method']:<28}"
                      f"{r['node'][:15]:<16}{r['calls']:>8}"
                      f"{r['total_s']:>10.4f}{r['mean_us']:>10.1f}")
            return
        for name in sorted(agg):
            m = agg[name]
            if m["kind"] == "histogram":
                total = sum(s.get("count", 0) for s in m["series"])
                hsum = sum(s.get("sum", 0.0) for s in m["series"])
                print(f"{name:<44}{m['kind']:<10}n={total} sum={hsum:.4f}s")
            else:
                val = sum(s.get("value", 0.0) for s in m["series"])
                print(f"{name:<44}{m['kind']:<10}{val:g}")
    finally:
        c.close()


def cmd_dashboard(args):
    import time as _time

    from ray_tpu.dashboard import DashboardHead

    head = DashboardHead(_resolve_address(args), port=args.port)
    print(f"dashboard serving at {head.url} (ctrl-c to stop)")
    try:
        while True:
            _time.sleep(1)
    except KeyboardInterrupt:
        head.shutdown()


def cmd_job(args):
    """Job CLI over the dashboard head's REST API (reference:
    python/ray/dashboard/modules/job/cli.py — also a thin HTTP client)."""
    import shlex
    import urllib.error
    import urllib.request

    base = f"http://{args.dashboard}"

    def req(path, payload=None):
        data = json.dumps(payload).encode() if payload is not None else None
        r = urllib.request.Request(
            base + path, data=data,
            headers={"Content-Type": "application/json"},
            method="POST" if data is not None else "GET",
        )
        try:
            with urllib.request.urlopen(r, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # the head returns JSON error bodies with 4xx — show them
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001
                body = {"error": str(e)}
            sys.exit(f"error: {body.get('error', body)}")
        except urllib.error.URLError as e:
            sys.exit(f"dashboard not reachable at {base}: {e.reason}")

    if args.job_command == "submit":
        ep = args.entrypoint
        if ep and ep[0] == "--":  # argparse REMAINDER keeps the separator
            ep = ep[1:]
        # shlex.join: the head re-parses this with shell=True, so each argv
        # element must survive re-quoting (spaces, -c scripts, metachars)
        body = {"entrypoint": shlex.join(ep)}
        if args.submission_id:
            body["submission_id"] = args.submission_id
        out = req("/api/jobs", body)
    elif args.job_command == "list":
        out = req("/api/jobs")
    elif args.job_command == "status":
        out = req(f"/api/jobs/{args.job_id}")
    elif args.job_command == "logs":
        out = req(f"/api/jobs/{args.job_id}/logs")
        print(out.get("logs", ""))
        return
    else:  # stop
        out = req(f"/api/jobs/{args.job_id}/stop", {})
    print(json.dumps(out, indent=2))


def cmd_up(args):
    from ray_tpu.autoscaler.launcher import cluster_up
    from ray_tpu.util.usage import record_event

    state = cluster_up(args.config)
    record_event("cluster_up", cluster=state["cluster_name"],
                 nodes=len(state["pids"]) - 1)
    print(f"cluster {state['cluster_name']!r} up at {state['address']} "
          f"({len(state['pids'])} processes)")
    print(f"connect with: ray_tpu.init(address={state['address']!r})")


def cmd_down(args):
    from ray_tpu.autoscaler.launcher import cluster_down

    killed = cluster_down(args.cluster)
    print(f"terminated {len(killed)} processes")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="head address for worker nodes")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--num-cpus", type=float, default=os.cpu_count() or 4)
    sp.add_argument("--num-tpus", type=float, default=0)
    sp.add_argument("--memory", type=float, default=0)
    sp.add_argument("--resources", help="extra resources as JSON")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop locally started nodes")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser(
        "stacks",
        help="dump all-thread stacks of every local cluster process, "
             "annotated with wait edges and held locks (waitgraph)")
    sp.add_argument("--timeout", type=float, default=2.5,
                    help="seconds to wait for the dumps (default 2.5)")
    sp.add_argument("--artifact-dir", default=None,
                    help="where the node processes write waitgraph "
                         "artifacts (default: $RAY_TPU_FLIGHTREC_DIR "
                         "or artifacts/)")
    sp.set_defaults(fn=cmd_stacks)

    for name, fn in (("status", cmd_status), ("summary", cmd_summary),
                     ("timeline", cmd_timeline), ("memory", cmd_memory)):
        sp = sub.add_parser(name)
        sp.add_argument("--address")
        sp.add_argument("--limit", type=int, default=1000)
        if name == "timeline":
            sp.add_argument("-o", "--output")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("list", help="list tasks/actors/nodes/objects/placement-groups/cluster-events")
    sp.add_argument("kind", choices=["tasks", "actors", "nodes", "objects",
                                     "placement-groups", "cluster-events"])
    sp.add_argument("--address")
    sp.add_argument("--limit", type=int, default=1000)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser(
        "metrics", help="cluster metrics: summary, --top handler ranking, "
        "--prom Prometheus text")
    sp.add_argument("--address")
    sp.add_argument("--top", action="store_true",
                    help="rank rpc handler self-time (GCS + daemons)")
    sp.add_argument("--prom", action="store_true",
                    help="print raw Prometheus exposition text")
    sp.add_argument("--limit", type=int, default=20)
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser("microbenchmark", help="single-node perf quick check")
    sp.add_argument("--address")
    sp.add_argument("--quick", action="store_true")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("dashboard", help="serve cluster state over HTTP/JSON")
    sp.add_argument("--address")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("job", help="submit/inspect jobs via the dashboard")
    jsub = sp.add_subparsers(dest="job_command", required=True)
    js = jsub.add_parser("submit", help="run an entrypoint as a job")
    js.add_argument("--dashboard", default="127.0.0.1:8265")
    js.add_argument("--submission-id", default=None)
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("list", "status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("--dashboard", default="127.0.0.1:8265")
        if name != "list":
            jp.add_argument("job_id")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config", help="cluster YAML path")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("cluster", help="cluster name or YAML path")
    sp.set_defaults(fn=cmd_down)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
