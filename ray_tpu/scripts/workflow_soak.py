"""Workflow resume soak: kill the driver mid-workflow repeatedly; every
completed step must execute exactly once across all resumes.

Run as: python -m ray_tpu.scripts.workflow_soak. A 12-step DAG's driver
is SIGKILLed every ~1.1-1.9s and re-run (workflow.run with the same id
resumes from storage). Last recorded run (2026-07-30): completed after
9 kills, 12/12 steps executed exactly once (zero re-executions — each
step's result persists before the next starts).
"""
import os, subprocess, sys, tempfile, time

root = tempfile.mkdtemp(prefix="wf_soak_")
driver = r'''
import json, os, sys, time
import ray_tpu
from ray_tpu import workflow

ray_tpu.init(num_cpus=4)
root = sys.argv[1]
marks = sys.argv[2]

def mark(tag):
    with open(marks, "a") as f:
        f.write(tag + "\n")

def s_fn(tag, *deps):
    time.sleep(0.25)
    mark(tag)
    return tag

s = workflow.step(s_fn)
# 12-step chain with some fan-in
a = s("a"); b = s("b", a); c = s("c", a)
d = s("d", b, c)
prev = d
for i in range(8):
    prev = s(f"e{i}", prev)
out = workflow.run(prev, "soak-wf", storage_root=root)
print("WF-DONE", out, flush=True)
'''
marks = os.path.join(root, "marks.txt")
attempts = 0
while attempts < 60:
    attempts += 1
    p = subprocess.Popen([sys.executable, "-c", driver, root, marks],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                         env=dict(os.environ, PYTHONPATH="/root/repo"))
    try:
        out, _ = p.communicate(timeout=1.1 + (attempts % 4) * 0.25)
        if "WF-DONE" in out:
            print("completed after", attempts, "attempts", flush=True)
            break
        print("attempt", attempts, "exited without done:", out[-200:], flush=True)
    except subprocess.TimeoutExpired:
        p.kill()
        p.communicate()
lines = open(marks).read().split()
from collections import Counter
dup = {k: v for k, v in Counter(lines).items() if v > 1}
expected = {"a", "b", "c", "d"} | {f"e{i}" for i in range(8)}
print("steps executed:", len(lines), "distinct:", len(set(lines)))
print("missing:", sorted(expected - set(lines)))
print("re-executed steps (should be FEW, only kills mid-step):", dup)
assert expected <= set(lines), "missing steps!"
print("WF SOAK OK")
