"""Serve soak: sustained handle traffic across replica rescaling.

Run as: python -m ray_tpu.scripts.serve_soak [seconds]. 4 hammer threads
drive a batched deployment while it is rescaled every few seconds;
handle_err must stay 0 (the router refreshes membership and resubmits on
dead replicas). Last recorded run (2026-07-30, 1-core host): 200s,
610,341 calls, 20 rescales, 0 errors — before the router retry landed,
the same soak produced 106k dead-replica errors.
"""
import random, sys, threading, time
import ray_tpu
from ray_tpu import serve

DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
random.seed(3)
ray_tpu.init(num_cpus=8)

@serve.deployment(num_replicas=2, max_ongoing_requests=8)
class Echo:
    def __init__(self):
        self.n = 0

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.02)
    def __call__(self, xs):
        self.n += len(xs)
        return [x * 2 for x in xs]

h = serve.run(Echo.bind(), name="soak")
stats = {"handle_ok": 0, "handle_err": 0, "rescale": 0}
stats_lock = threading.Lock()
stop = []

def hammer():
    # dict += from several threads loses increments; count under a lock
    while not stop:
        i = random.randint(0, 10_000)
        try:
            r = h.remote(i).result(timeout=30)
            assert r == i * 2, (r, i)
            with stats_lock:
                stats["handle_ok"] += 1
        except Exception as e:
            with stats_lock:
                stats["handle_err"] += 1
            print("HANDLE ERR:", repr(e)[:120], flush=True)

threads = [threading.Thread(target=hammer) for _ in range(4)]
for t in threads: t.start()
t_end = time.time() + DURATION
last = time.time()
while time.time() < t_end:
    time.sleep(5)
    if random.random() < 0.5:
        # rescale the deployment up/down through a re-run
        n = random.choice([1, 2, 3])
        serve.run(Echo.options(num_replicas=n).bind(), name="soak")
        stats["rescale"] += 1
    if time.time() - last > 30:
        print("t=%.0f %s" % (DURATION - (t_end - time.time()), stats), flush=True)
        last = time.time()
stop.append(1)
for t in threads: t.join(timeout=60)
print("FINAL:", stats, flush=True)
serve.shutdown()
ray_tpu.shutdown()
