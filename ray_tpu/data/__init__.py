"""ray_tpu.data — streaming datasets over the core task/actor API.

Reference: python/ray/data/ (Dataset, read_api, grouped_data, aggregate).
"""

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import ActorPoolStrategy, Dataset
from ray_tpu.data.grouped import (
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.io import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004
    read_csv,
    read_json,
    read_parquet,
)

__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "BlockAccessor",
    "Count",
    "Dataset",
    "Max",
    "Mean",
    "Min",
    "Std",
    "Sum",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_csv",
    "read_json",
    "read_parquet",
]
