"""Datasource read/write for ray_tpu.data.

Reference: python/ray/data/read_api.py (range/from_items/read_parquet/...)
and _internal/datasource/ (parquet/csv/json datasources). Reads produce one
remote task per file/shard so IO parallelizes through the scheduler like any
other work; blocks land in the object store.
"""

from __future__ import annotations

import glob
import os
from typing import Any, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import block_from_rows
from ray_tpu.data.dataset import Dataset


@ray_tpu.remote
def _read_shard(kind: str, path_or_args: Any, kwargs: dict = None) -> pa.Table:
    kwargs = kwargs or {}
    if kind == "range":
        start, stop = path_or_args
        return pa.table({"id": pa.array(np.arange(start, stop))})
    if kind == "parquet":
        import pyarrow.parquet as pq

        return pq.read_table(path_or_args, **kwargs)
    if kind == "csv":
        from pyarrow import csv as pacsv

        return pacsv.read_csv(path_or_args, **kwargs)
    if kind == "json":
        from pyarrow import json as pajson

        return pajson.read_json(path_or_args, **kwargs)
    raise ValueError(kind)


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


_range = range  # the module-level read API shadows the builtin below


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = min(200, max(1, n // 1000)) if n else 1
    cuts = [n * i // parallelism for i in _range(parallelism + 1)]
    refs = [
        _read_shard.remote("range", (cuts[i], cuts[i + 1]))
        for i in _range(parallelism)
    ]
    return Dataset(refs)


def from_items(items: List[Any], *, parallelism: int = 4) -> Dataset:
    n = len(items)
    parallelism = max(1, min(parallelism, n or 1))
    cuts = [n * i // parallelism for i in _range(parallelism + 1)]
    refs = [
        ray_tpu.put(block_from_rows(items[cuts[i]:cuts[i + 1]]))
        for i in _range(parallelism)
    ]
    return Dataset(refs)


def from_numpy(arr: np.ndarray, *, column: str = "data") -> Dataset:
    return from_items([{column: row} for row in arr])


def from_pandas(df) -> Dataset:
    return Dataset([ray_tpu.put(pa.Table.from_pandas(df, preserve_index=False))])


def from_arrow(table: pa.Table) -> Dataset:
    return Dataset([ray_tpu.put(table)])


def read_parquet(paths, **kwargs) -> Dataset:
    """kwargs forward to pyarrow.parquet.read_table (columns=, filters=, ...)."""
    return Dataset(
        [_read_shard.remote("parquet", p, kwargs) for p in _expand_paths(paths)]
    )


def read_csv(paths, **kwargs) -> Dataset:
    """kwargs forward to pyarrow.csv.read_csv (read_options=, ...)."""
    return Dataset(
        [_read_shard.remote("csv", p, kwargs) for p in _expand_paths(paths)]
    )


def read_json(paths, **kwargs) -> Dataset:
    """kwargs forward to pyarrow.json.read_json."""
    return Dataset(
        [_read_shard.remote("json", p, kwargs) for p in _expand_paths(paths)]
    )


def _write_blocks(ds: Dataset, path: str, fmt: str) -> None:
    os.makedirs(path, exist_ok=True)
    for i, block in enumerate(ds.iter_blocks()):
        if block.num_rows == 0:
            continue
        fp = os.path.join(path, f"part-{i:05d}.{fmt}")
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(block, fp)
        elif fmt == "csv":
            from pyarrow import csv as pacsv

            pacsv.write_csv(block, fp)
        elif fmt == "json":
            block.to_pandas().to_json(fp, orient="records", lines=True)
