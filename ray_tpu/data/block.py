"""Block representation for ray_tpu.data.

Reference: python/ray/data/block.py (Block = pyarrow.Table, BlockAccessor).
Canonical block format is a pyarrow.Table (zero-copy into the shm object
store via Arrow IPC; zero-copy out to numpy for device feeds), same choice
as the reference. Rows are plain dicts; batches convert to numpy / pandas /
pyarrow on demand.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa


def _normalize_rows(rows: List[Any]) -> pa.Table:
    """Items -> table. Non-dict items land in the reference's magic
    'item' column (python/ray/data/_internal/util.py)."""
    if rows and isinstance(rows[0], dict):
        if not all(isinstance(r, dict) for r in rows):
            raise TypeError("cannot mix dict and non-dict items in one block")
        # column set = union across ALL rows (missing values become null)
        keys: List[str] = []
        seen = set()
        for r in rows:
            for k in r:
                if k not in seen:
                    seen.add(k)
                    keys.append(k)
        cols = {k: [r.get(k) for r in rows] for k in keys}
        return pa.table({k: _to_array(v) for k, v in cols.items()})
    return pa.table({"item": _to_array(list(rows))})


def _to_array(values: list) -> pa.Array:
    if values and isinstance(values[0], np.ndarray):
        # tensor column: fixed-shape ndarray per row
        flat = np.stack(values)
        return pa.FixedSizeListArray.from_arrays(
            pa.array(flat.reshape(flat.shape[0], -1).ravel()),
            int(np.prod(flat.shape[1:])),
        )
    return pa.array(values)


def block_from_rows(rows: List[Any]) -> pa.Table:
    if not rows:
        return pa.table({})
    return _normalize_rows(rows)


def block_from_batch(batch: Any) -> pa.Table:
    """A user batch (dict of numpy arrays / pandas DataFrame / pyarrow Table /
    list of rows) -> block."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            if isinstance(v, list):
                cols[k] = _to_array(v)
            else:
                arr = np.asarray(v)
                if arr.ndim > 1:
                    # tensor column: keep per-row shape via fixed-size lists
                    cols[k] = pa.FixedSizeListArray.from_arrays(
                        pa.array(arr.reshape(arr.shape[0], -1).ravel()),
                        int(np.prod(arr.shape[1:])),
                    )
                else:
                    cols[k] = pa.array(arr)
        return pa.table(cols)
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(batch, list):
        return block_from_rows(batch)
    raise TypeError(f"unsupported batch type: {type(batch)}")


class BlockAccessor:
    """Uniform view over a block (reference: BlockAccessor.for_block)."""

    def __init__(self, block: pa.Table):
        self._t = block

    @staticmethod
    def for_block(block: pa.Table) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self._t.num_rows

    def size_bytes(self) -> int:
        return self._t.nbytes

    def schema(self):
        return self._t.schema

    def to_arrow(self) -> pa.Table:
        return self._t

    def to_pandas(self):
        return self._t.to_pandas()

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out = {}
        for name in self._t.column_names:
            col = self._t.column(name)
            if pa.types.is_fixed_size_list(col.type):
                flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
                out[name] = flat.reshape(self._t.num_rows, -1)
            elif pa.types.is_list(col.type):
                # equal-length list rows (e.g. tensor rows that round-tripped
                # through python) stack back into a 2-D batch
                rows = col.to_pylist()
                try:
                    out[name] = np.stack([np.asarray(r) for r in rows])
                except ValueError:
                    out[name] = np.asarray(rows, dtype=object)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_batch(self, batch_format: Optional[str]):
        if batch_format in (None, "default", "numpy"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self._t
        raise ValueError(f"unknown batch_format: {batch_format}")

    def iter_rows(self) -> Iterator[dict]:
        cols = self._t.column_names
        if cols == ["item"]:
            for v in self._t.column("item").to_pylist():
                yield v
            return
        for row in self._t.to_pylist():
            yield row

    def slice(self, start: int, end: int) -> pa.Table:
        return self._t.slice(start, end - start)


def concat_blocks(blocks: List[pa.Table]) -> pa.Table:
    blocks = [b for b in blocks if b.num_rows > 0]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks, promote_options="default")
