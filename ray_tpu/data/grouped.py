"""GroupedData: hash-partitioned groupby aggregation.

Reference: python/ray/data/grouped_data.py (GroupedData.count/sum/mean/...,
AggregateFn). Map side hashes the key into n partitions; each reduce task
runs pyarrow's native group_by over its partition — all groups with equal
keys land in the same partition, so per-partition aggregates are exact.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor, concat_blocks
from ray_tpu.data.dataset import Dataset, _AllToAllStage


def _det_hash(v) -> int:
    """Deterministic cross-process hash (Python's hash() is salted per
    process — worker subprocesses would partition the same key
    differently)."""
    import zlib

    return zlib.crc32(repr(v).encode())


def _hash_partition(key: str):
    """part_fn for hash exchanges: rows with equal keys land in the same
    partition in every worker process."""

    def part(block, n, _key=key):
        if block.num_rows == 0:
            return [block] * n
        vals = block.column(_key).to_pylist()
        h = np.array([_det_hash(v) % n for v in vals])
        return [block.take(pa.array(np.nonzero(h == j)[0])) for j in range(n)]

    return part


class AggregateFn:
    """Named aggregate over a column (reference: ray.data.aggregate.AggregateFn
    family — Count/Sum/Min/Max/Mean/Std)."""

    def __init__(self, kind: str, on: Optional[str] = None, alias: Optional[str] = None):
        self.kind = kind
        self.on = on
        self.alias = alias or (f"{kind}({on})" if on else kind)


def Count():
    return AggregateFn("count")


def Sum(on: str):
    return AggregateFn("sum", on)


def Min(on: str):
    return AggregateFn("min", on)


def Max(on: str):
    return AggregateFn("max", on)


def Mean(on: str):
    return AggregateFn("mean", on)


def Std(on: str):
    return AggregateFn("stddev", on)


_PA_AGG = {
    "count": "count",
    "sum": "sum",
    "min": "min",
    "max": "max",
    "mean": "mean",
    "stddev": "stddev",
}


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        key = self._key
        n = max(self._ds.num_blocks(), 1)
        agg_spec: List[Tuple[str, str, str]] = []
        for a in aggs:
            col = a.on if a.on else key
            agg_spec.append((col, _PA_AGG[a.kind], a.alias))

        def reduce(blocks, _key=key, _spec=tuple(agg_spec)):
            t = concat_blocks(blocks)
            if t.num_rows == 0:
                return t
            gb = t.group_by(_key)
            res = gb.aggregate([(col, fn) for col, fn, _ in _spec])
            # rename pyarrow's col_fn names to the requested aliases
            names = list(res.column_names)
            for col, fn, alias in _spec:
                pa_name = f"{col}_{fn}"
                if pa_name in names:
                    names[names.index(pa_name)] = alias
            return res.rename_columns(names)

        return self._ds._with_stage(
            _AllToAllStage("groupby", n, _hash_partition(key), reduce)
        )

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn) -> Dataset:
        """Apply fn(pandas.DataFrame) -> rows/DataFrame per group."""
        key = self._key
        n = max(self._ds.num_blocks(), 1)

        def reduce(blocks, _key=key):
            from ray_tpu.data.block import block_from_batch

            t = concat_blocks(blocks)
            if t.num_rows == 0:
                return t
            df = t.to_pandas()
            outs = []
            for _, group in df.groupby(_key, sort=False):
                out = fn(group)
                outs.append(block_from_batch(out))
            return concat_blocks(outs)

        return self._ds._with_stage(
            _AllToAllStage("map_groups", n, _hash_partition(key), reduce)
        )
