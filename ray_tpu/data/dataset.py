"""Dataset: lazy logical plan + streaming block execution over the core API.

Reference surfaces: python/ray/data/dataset.py (user API),
_internal/execution/streaming_executor.py (windowed, memory-bounded block
processing), _internal/logical/ (plan + fusion rules), operators/
map_operator.py and actor_pool_map_operator.py (task vs actor compute).

Design: a Dataset is (input block producers, list of stages). Stages are
either per-block transforms (fused greedily, executed as a pipelined stream
of remote tasks with a bounded in-flight window) or all-to-all exchanges
(repartition / shuffle / sort / groupby — map-side partition tasks feeding
reduce tasks, the push-based-shuffle shape from
_internal/planner/exchange/). Blocks are pyarrow Tables in the object store.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import (
    BlockAccessor,
    block_from_batch,
    block_from_rows,
    concat_blocks,
)

# ----------------------------------------------------------------- remote fns


@ray_tpu.remote
def _map_block(fn, block):
    return fn(block)


@ray_tpu.remote
def _partition_block(part_fn, n, idx, block):
    """Map side of an exchange: split one block into n partition blocks.
    With n == 1 the single block is returned bare (num_returns=1 stores the
    return value itself, not a 1-tuple)."""
    if getattr(part_fn, "_wants_index", False):
        parts = list(part_fn(block, n, idx))
    else:
        parts = list(part_fn(block, n))
    return parts[0] if n == 1 else tuple(parts)


@ray_tpu.remote(num_returns="streaming")
def _partition_block_stream(part_fn, n, idx, block):
    """Streaming map side of an exchange (reference: the push-based
    shuffle / streaming-generator exchange in ray.data): each partition
    is PUBLISHED as it is produced — a separate store object shipped
    mid-task — instead of all n riding the task's completion as one
    result set. Partition i of every map task is consumable while the
    slower maps still run."""
    if getattr(part_fn, "_wants_index", False):
        yield from part_fn(block, n, idx)
    else:
        yield from part_fn(block, n)


@ray_tpu.remote
def _count_rows(block):
    return BlockAccessor(block).num_rows()


@ray_tpu.remote
def _slice_block(block, start, stop):
    return BlockAccessor(block).slice(start, stop)


@ray_tpu.remote
def _reduce_blocks(reduce_fn, idx, *parts):
    if getattr(reduce_fn, "_wants_index", False):
        return reduce_fn(list(parts), idx)
    return reduce_fn(list(parts))


@ray_tpu.remote
class _MapActor:
    """Actor-pool compute for map_batches with stateful callables
    (reference: actor_pool_map_operator.py)."""

    def __init__(self, fn_ctor):
        self._fn = fn_ctor()

    def apply(self, wrapper, block):
        return wrapper(self._fn, block)


# --------------------------------------------------------------------- stages


class _MapStage:
    def __init__(self, fn: Callable, name: str, compute=None, fn_ctor=None):
        self.fn = fn  # block -> block   (or (state, block) -> block w/ actors)
        self.name = name
        self.compute = compute
        self.fn_ctor = fn_ctor

    def fuse(self, other: "_MapStage") -> Optional["_MapStage"]:
        if self.compute is not None or other.compute is not None:
            return None
        f, g = self.fn, other.fn

        def fused(block):
            return g(f(block))

        return _MapStage(fused, f"{self.name}->{other.name}")


class _AllToAllStage:
    def __init__(self, name, n_outputs, part_fn, reduce_fn, prepare=None):
        self.name = name
        self.n_outputs = n_outputs
        self.part_fn = part_fn  # (block, n) -> [n blocks]
        self.reduce_fn = reduce_fn  # [blocks] -> block
        # optional pre-pass over the materialized input refs (e.g. boundary
        # sampling for sort); returns a replacement part_fn
        self.prepare = prepare


class _LimitStage:
    def __init__(self, n: int):
        self.n = n


DEFAULT_IN_FLIGHT = 16


class ActorPoolStrategy:
    """compute= argument for map_batches (reference: ray.data.ActorPoolStrategy)."""

    def __init__(self, size: int = 2):
        self.size = size


# ------------------------------------------------------------------ execution


def _execute_map(refs: Iterator, stage: _MapStage, window: int) -> Iterator:
    """Pipelined per-block execution with a bounded in-flight window.

    Yields outputs in SUBMISSION order (block order is part of Dataset
    semantics — take()/zip() depend on it), waiting on the head of the
    window while the rest keep running."""
    if stage.compute is not None:
        yield from _execute_map_actors(refs, stage)
        return
    in_flight: List = []
    for ref in refs:
        in_flight.append(_map_block.remote(stage.fn, ref))
        if len(in_flight) >= window:
            ray_tpu.wait([in_flight[0]], num_returns=1)
            yield in_flight.pop(0)
    while in_flight:
        ray_tpu.wait([in_flight[0]], num_returns=1)
        yield in_flight.pop(0)


def _execute_map_actors(refs: Iterator, stage: _MapStage) -> Iterator:
    pool = [_MapActor.remote(stage.fn_ctor) for _ in range(stage.compute.size)]
    try:
        in_flight = []
        for i, ref in enumerate(refs):
            actor = pool[i % len(pool)]
            in_flight.append(actor.apply.remote(stage.fn, ref))
            if len(in_flight) >= 2 * len(pool):
                ray_tpu.wait([in_flight[0]], num_returns=1)
                yield in_flight.pop(0)
        while in_flight:
            ray_tpu.wait([in_flight[0]], num_returns=1)
            yield in_flight.pop(0)
    finally:
        # pool actors hold their CPUs for life; leaking them across
        # re-executions starves the cluster and deadlocks actor creation
        for a in pool:
            ray_tpu.kill(a)


def _execute_all_to_all(refs: List, stage: _AllToAllStage) -> List:
    n = stage.n_outputs
    part_fn = stage.part_fn
    if stage.prepare is not None:
        part_fn = stage.prepare(refs)
    if n == 1:
        parts = [
            [_partition_block.remote(part_fn, n, i, ref)]
            for i, ref in enumerate(refs)
        ]
    else:
        # streaming exchange: every map task publishes partitions as it
        # produces them; consuming the generators overlaps partitioning
        # with transfer across the whole map wave
        gens = [
            _partition_block_stream.remote(part_fn, n, i, ref)
            for i, ref in enumerate(refs)
        ]
        parts = [list(g) for g in gens]
        for i, (g, p) in enumerate(zip(gens, parts)):
            if g.errored:
                # the stream's last ref carries the partitioner's real
                # exception — surface IT, not a block-count mismatch (and
                # never hand the error marker to a reduce task as data)
                ray_tpu.get(p[-1])
            if len(p) != n:
                raise ValueError(
                    f"exchange partitioner produced {len(p)} blocks for "
                    f"input {i}, expected {n}"
                )
    out = []
    for j in range(n):
        out.append(
            _reduce_blocks.remote(stage.reduce_fn, j, *[p[j] for p in parts])
        )
    return out


# -------------------------------------------------------------------- dataset


class Dataset:
    """Lazy, immutable, distributed collection of rows (reference:
    python/ray/data/dataset.py Dataset)."""

    def __init__(self, block_refs: List, stages: Optional[List] = None):
        self._input_refs = block_refs
        self._stages = stages or []
        # set by union(): input blocks come from the parents' pipelines,
        # executed lazily at consumption time
        self._parents: Optional[List["Dataset"]] = None

    # ------------------------------------------------------------- transforms

    def _with_stage(self, stage) -> "Dataset":
        stages = list(self._stages)
        if stages and isinstance(stage, _MapStage) and isinstance(stages[-1], _MapStage):
            fused = stages[-1].fuse(stage)
            if fused is not None:
                stages[-1] = fused
                return self._copy_with(stages)
        stages.append(stage)
        return self._copy_with(stages)

    def _copy_with(self, stages) -> "Dataset":
        ds = Dataset(self._input_refs, stages)
        ds._parents = self._parents
        return ds

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        def _map(block):
            return block_from_rows([fn(r) for r in BlockAccessor(block).iter_rows()])

        return self._with_stage(_MapStage(_map, "map"))

    def flat_map(self, fn: Callable[[dict], List[dict]]) -> "Dataset":
        def _fmap(block):
            out = []
            for r in BlockAccessor(block).iter_rows():
                out.extend(fn(r))
            return block_from_rows(out)

        return self._with_stage(_MapStage(_fmap, "flat_map"))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        def _filt(block):
            # mask-based: preserves the schema even when every row drops
            mask = [bool(fn(r)) for r in BlockAccessor(block).iter_rows()]
            return block.filter(pa.array(mask, type=pa.bool_()))

        return self._with_stage(_MapStage(_filt, "filter"))

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_format: Optional[str] = "numpy",
        batch_size: Optional[int] = None,
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
        **kwargs,
    ) -> "Dataset":
        """Apply fn to batches. A callable CLASS runs on an actor pool with
        one instance per actor (stateful, e.g. a jitted model)."""
        is_class = isinstance(fn, type)
        if is_class and compute is None:
            compute = ActorPoolStrategy(size=2)

        def _apply(callable_fn, block):
            acc = BlockAccessor(block)
            nrows = acc.num_rows()
            if nrows == 0:
                if block.num_columns == 0:
                    # schema-less empty: nothing the fn could act on
                    return block
                # empty but typed: run the fn so the OUTPUT schema is right;
                # only empty-batch-shaped failures (indexing/reducing zero
                # rows) fall back to the input block — real fn bugs propagate
                try:
                    return block_from_batch(
                        callable_fn(acc.to_batch(batch_format))
                    )
                except (IndexError, ValueError, ZeroDivisionError, StopIteration):
                    return block
            size = batch_size or nrows
            outs = []
            for s in range(0, nrows, size):
                sub = acc.slice(s, min(s + size, nrows))
                out = callable_fn(BlockAccessor(sub).to_batch(batch_format))
                outs.append(block_from_batch(out))
            return concat_blocks(outs)

        if is_class:
            ctor = (lambda: fn(*fn_constructor_args))
            return self._with_stage(
                _MapStage(_apply, "map_batches(actors)", compute=compute, fn_ctor=ctor)
            )

        def _task(block):
            return _apply(fn, block)

        return self._with_stage(_MapStage(_task, "map_batches"))

    def add_column(self, name: str, fn) -> "Dataset":
        def _add(block):
            col = fn(BlockAccessor(block).to_numpy())
            return block.append_column(name, pa.array(np.asarray(col)))

        return self._with_stage(_MapStage(_add, f"add_column({name})"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def _drop(block):
            return block.drop_columns(cols)

        return self._with_stage(_MapStage(_drop, "drop_columns"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def _sel(block):
            return block.select(cols)

        return self._with_stage(_MapStage(_sel, "select_columns"))

    # ---------------------------------------------------------- all-to-all ops

    def repartition(self, num_blocks: int) -> "Dataset":
        def part(block, n):
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            cuts = [rows * i // n for i in range(n + 1)]
            return [acc.slice(cuts[i], cuts[i + 1]) for i in range(n)]

        return self._with_stage(
            _AllToAllStage("repartition", num_blocks, part, concat_blocks)
        )

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        n = max(self.num_blocks(), 1)

        def part(block, n, idx, _seed=seed):
            # seed salted per block index: every map task draws an
            # independent stream (reference: shuffle ops seed per task)
            rng = np.random.default_rng(
                None if _seed is None else (_seed, 0, idx)
            )
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            assign = rng.integers(0, n, rows)
            t = block
            return [
                t.take(pa.array(np.nonzero(assign == j)[0])) for j in range(n)
            ]

        part._wants_index = True

        def reduce(blocks, idx, _seed=seed):
            t = concat_blocks(blocks)
            rng = np.random.default_rng(
                None if _seed is None else (_seed, 1, idx)
            )
            if t.num_rows:
                t = t.take(pa.array(rng.permutation(t.num_rows)))
            return t

        reduce._wants_index = True

        return self._with_stage(_AllToAllStage("random_shuffle", n, part, reduce))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Sample → range-partition → per-partition sort (reference:
        _internal/planner/exchange/sort_task_spec.py). Boundary sampling
        runs as a prepare pass over the materialized input refs, so
        partition j holds exactly the j-th key range: concatenating the
        output blocks in order IS the global sort order."""
        n = max(self.num_blocks(), 1)
        order = "descending" if descending else "ascending"

        def prepare(refs, _key=key, _n=n):
            @ray_tpu.remote
            def sample(block):
                col = block.column(_key)
                m = min(block.num_rows, 64)
                if m == 0:
                    return []
                idx = np.linspace(0, block.num_rows - 1, m).astype(np.int64)
                return [col[int(i)].as_py() for i in idx]

            samples = sorted(
                s for chunk in ray_tpu.get([sample.remote(r) for r in refs])
                for s in chunk
            )
            if not samples:
                bounds = []
            else:
                bounds = [
                    samples[len(samples) * j // _n]
                    for j in range(1, _n)
                ]
            if descending:
                bounds = bounds[::-1]

            def part(block, n, _bounds=tuple(bounds), _desc=descending):
                if block.num_rows == 0:
                    return [block] * n
                vals = block.column(_key).to_pylist()
                # partition index = number of boundaries crossed; descending
                # bounds are reversed so partition 0 holds the largest keys
                assign = np.zeros(len(vals), np.int64)
                for b in _bounds:
                    crossed = [(v < b) if _desc else (v >= b) for v in vals]
                    assign += np.array(crossed, np.int64)
                assign = np.clip(assign, 0, n - 1)
                return [
                    block.take(pa.array(np.nonzero(assign == j)[0]))
                    for j in range(n)
                ]

            return part

        def reduce(blocks, _key=key, _order=order):
            t = concat_blocks(blocks)
            if t.num_rows == 0:
                return t
            return t.take(pa.compute.sort_indices(t, sort_keys=[(_key, _order)]))

        return self._with_stage(
            _AllToAllStage("sort", n, None, reduce, prepare=prepare)
        )

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    def union(self, other: "Dataset") -> "Dataset":
        """Lazy: neither input pipeline executes until the union is consumed."""
        ds = Dataset([])
        ds._parents = [self, other]
        return ds

    def zip(self, other: "Dataset") -> "Dataset":
        left = concat_blocks(ray_tpu.get(self._materialize_refs()))
        right = concat_blocks(ray_tpu.get(other._materialize_refs()))
        if left.num_rows != right.num_rows:
            raise ValueError("zip: datasets must have equal row counts")
        for name in right.column_names:
            out_name = name if name not in left.column_names else name + "_1"
            left = left.append_column(out_name, right.column(name))
        return Dataset([ray_tpu.put(left)])

    def limit(self, n: int) -> "Dataset":
        return self._copy_with(list(self._stages) + [_LimitStage(n)])

    def split(self, n: int) -> List["Dataset"]:
        refs = self.repartition(n)._materialize_refs()
        return [Dataset([r]) for r in refs]

    # ------------------------------------------------------------- execution

    def _execute_refs(self) -> Iterator:
        window = DEFAULT_IN_FLIGHT
        if self._parents is not None:
            refs: Iterator = (
                r for p in self._parents for r in p._execute_refs()
            )
        else:
            refs = iter(self._input_refs)
        for stage in self._stages:
            if isinstance(stage, _MapStage):
                refs = _execute_map(refs, stage, window)
            elif isinstance(stage, _AllToAllStage):
                refs = iter(_execute_all_to_all(list(refs), stage))
            elif isinstance(stage, _LimitStage):
                # applied at its position in the plan: later stages only see
                # the truncated stream
                refs = self._apply_limit(refs, stage.n)
        yield from refs

    @staticmethod
    def _apply_limit(refs, n):
        # count/slice remotely: only the row count crosses to the driver,
        # never the block contents (reference: limit uses block metadata)
        taken = 0
        for ref in refs:
            if taken >= n:
                break
            rows = ray_tpu.get(_count_rows.remote(ref))
            if taken + rows <= n:
                taken += rows
                yield ref
            else:
                yield _slice_block.remote(ref, 0, n - taken)
                taken = n

    def _materialize_refs(self) -> List:
        return list(self._execute_refs())

    def materialize(self) -> "Dataset":
        return Dataset(self._materialize_refs())

    # ------------------------------------------------------------ consumption

    def iter_blocks(self) -> Iterator[pa.Table]:
        for r in self._execute_refs():
            yield ray_tpu.get(r)

    def iter_rows(self) -> Iterator[dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(
        self, *, batch_size: int = 256, batch_format: Optional[str] = "numpy"
    ) -> Iterator[Any]:
        for block in self.iter_blocks():
            acc = BlockAccessor(block)
            for s in range(0, acc.num_rows(), batch_size):
                sub = acc.slice(s, min(s + batch_size, acc.num_rows()))
                yield BlockAccessor(sub).to_batch(batch_format)

    def iter_torch_batches(
        self, *, batch_size: int = 256, dtypes=None, device: str = "cpu"
    ) -> Iterator[Any]:
        """Batches as dicts of torch tensors (reference:
        Dataset.iter_torch_batches; numpy batches zero-copy into
        torch.from_numpy where dtypes permit)."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy"
        ):
            out = {}
            for k, v in batch.items():
                t = torch.from_numpy(np.ascontiguousarray(v))
                if dtypes is not None:
                    want = dtypes.get(k) if isinstance(dtypes, dict) else dtypes
                    if want is not None:
                        t = t.to(want)
                if device != "cpu":
                    t = t.to(device)
                out[k] = t
            yield out

    def take(self, n: int = 20) -> List[Any]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def take_batch(self, n: int = 20, *, batch_format: str = "numpy"):
        rows = self.take(n)
        return BlockAccessor(block_from_rows(rows)).to_batch(batch_format)

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self.iter_blocks())

    def schema(self):
        for block in self.iter_blocks():
            if block.num_rows or block.num_columns:
                return BlockAccessor(block).schema()
        return None

    def num_blocks(self) -> int:
        if self._parents is not None:
            return sum(p.num_blocks() for p in self._parents)
        return len(self._input_refs)

    def to_pandas(self):
        return concat_blocks(list(self.iter_blocks())).to_pandas()

    def to_arrow(self) -> pa.Table:
        return concat_blocks(list(self.iter_blocks()))

    def stats(self) -> str:
        return (
            f"Dataset(blocks={self.num_blocks()}, "
            f"stages={[getattr(s, 'name', 'limit') for s in self._stages]})"
        )

    # ---------------------------------------------------------------- writes

    def write_parquet(self, path: str) -> None:
        from ray_tpu.data.io import _write_blocks

        _write_blocks(self, path, "parquet")

    def write_csv(self, path: str) -> None:
        from ray_tpu.data.io import _write_blocks

        _write_blocks(self, path, "csv")

    def write_json(self, path: str) -> None:
        from ray_tpu.data.io import _write_blocks

        _write_blocks(self, path, "json")

    def __repr__(self):
        return self.stats()
