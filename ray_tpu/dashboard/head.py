"""HTTP/JSON dashboard head over the state API.

Reference: python/ray/dashboard/head.py (aiohttp app aggregating GCS
state) and modules/state/state_head.py (the `/api/...` state routes).
stdlib ThreadingHTTPServer here — the image has no aiohttp, and the
endpoint surface is the component, not the web stack.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional


class DashboardHead:
    """Serves cluster state as JSON; one instance per driver/head.

    Endpoints (all GET):
      /api/summary              cluster counts
      /api/nodes                node table
      /api/actors               actor table
      /api/tasks?limit=N        recent task events
      /api/placement_groups     PG table
      /api/cluster_resources    total resources
      /api/available_resources  free resources
      /                         endpoint index
    """

    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        from ray_tpu.core.config import Config
        from ray_tpu.cluster.client import ClusterClient

        # a state-only consumer: don't subscribe this process to the whole
        # cluster's worker-log fanout
        self._client = ClusterClient(
            gcs_address, config=Config({"log_to_driver": False})
        )
        head = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet access log
                pass

            def do_GET(self):
                try:
                    body, status = head._route(self.path)
                except Exception as e:  # noqa: BLE001
                    body, status = {"error": repr(e)}, 500
                try:
                    data = json.dumps(body, default=str).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except OSError:
                    pass  # client hung up / head shutting down mid-request

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard-head",
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _route(self, path: str):
        route, _, query = path.partition("?")
        params: Dict[str, str] = {}
        for pair in query.split("&"):
            if "=" in pair:
                k, _, v = pair.partition("=")
                params[k] = v
        c = self._client
        if route in ("/", "/api"):
            return {
                "endpoints": [
                    "/api/summary", "/api/nodes", "/api/actors",
                    "/api/tasks?limit=N", "/api/placement_groups",
                    "/api/cluster_resources", "/api/available_resources",
                ]
            }, 200
        if route == "/api/summary":
            return c.summary(), 200
        if route == "/api/nodes":
            return c.nodes(), 200
        if route == "/api/actors":
            return c.list_actors(), 200
        if route == "/api/tasks":
            return c.list_tasks(int(params.get("limit", 1000))), 200
        if route == "/api/placement_groups":
            return c.list_placement_groups(), 200
        if route == "/api/cluster_resources":
            return c.cluster_resources(), 200
        if route == "/api/available_resources":
            return c.available_resources(), 200
        return {"error": f"unknown route {route}"}, 404

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()  # release the listening socket now
        self._client.shutdown()
